"""Cluster-file bootstrap — fdb.cluster parsing + coordinator discovery
(fdbclient/MonitorLeader.actor.cpp:435 parsing; the MonitorLeader poll
that turns a coordinator list into a live server address).

Format (the reference's): one line, `description:id@ip:port,ip:port,...`.
Comments (#) and blank lines are ignored.

`discover_gateway` quorum-reads the coordinators' LEADER register and
returns the client-gateway address the current cluster server published —
the bootstrap path a real multi-OS-process deployment uses:

    coordinators (tools/coordserver.py, N OS processes)
        ^ cstate + leader registers over real TCP
    server (tools/server.py --cluster-file)  -> publishes gateway addr
    client (this module)                     -> reads it, connects
"""

from __future__ import annotations

import os

from ..rpc.network import Endpoint, NetworkAddress


def parse_cluster_file(path: str) -> tuple[str, list[NetworkAddress]]:
    """Returns (description_id, coordinator addresses)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            head, _, addrs = line.partition("@")
            if not addrs:
                raise ValueError(f"{path}: malformed cluster file line {line!r}")
            out = []
            for a in addrs.split(","):
                ip, _, port = a.strip().rpartition(":")
                out.append(NetworkAddress(ip, int(port)))
            return head, out
    raise ValueError(f"{path}: no connection string found")


def write_cluster_file(path: str, addrs: list[NetworkAddress],
                       description: str = "fdbtpu:cluster") -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(description + "@" + ",".join(f"{a.ip}:{a.port}" for a in addrs) + "\n")
    os.replace(tmp, path)


def leader_refs(net, process, coords: list[NetworkAddress], write: bool = False):
    """RequestStreamRefs to every coordinator's leader register."""
    from ..rpc.stream import RequestStreamRef
    from ..tools.coordserver import LEADER_TOKENS

    tok = LEADER_TOKENS[1] if write else LEADER_TOKENS[0]
    return [
        RequestStreamRef(net, process, Endpoint(a, tok)) for a in coords
    ]


def cstate_refs(net, process, coords: list[NetworkAddress], write: bool = False):
    """RequestStreamRefs to every coordinator's cluster-state register."""
    from ..control.coordination import Coordinator
    from ..rpc.stream import RequestStreamRef

    tok = Coordinator.WLT_WRITE if write else Coordinator.WLT_READ
    return [
        RequestStreamRef(net, process, Endpoint(a, tok)) for a in coords
    ]


def discover_gateway(path: str, timeout: float = 10.0) -> tuple[str, int]:
    """MonitorLeader for clients: read the cluster file, quorum-read the
    leader register, return the published (host, port) of the client
    gateway.  Raises TimedOut when no quorum answers or no leader is
    published within `timeout`.

    All pacing routes through the bound clock: the NetDriver anchors the
    loop's virtual time to the wall, so `loop.now()` deadlines and
    driver-driven `loop.delay()` backoffs replace raw monotonic reads —
    and the retry backoff keeps PUMPING the network instead of blocking
    the process in time.sleep (a late quorum reply now lands during the
    backoff rather than after it)."""
    from ..control.coordination import CoordinatedState
    from ..rpc.transport import NetDriver, RealNetwork
    from ..runtime.core import EventLoop, TimedOut

    _desc, coords = parse_cluster_file(path)
    loop = EventLoop()
    net = RealNetwork(loop, name=f"client-{os.getpid()}")
    try:
        cs = CoordinatedState(
            loop,
            leader_refs(net, net.process, coords),
            leader_refs(net, net.process, coords, write=True),
            owner=f"client-{os.getpid()}",
        )
        driver = NetDriver(loop, net)

        def backoff() -> None:
            driver.run_until(loop.spawn(_delay_only(loop, 0.2)))

        deadline = loop.now() + timeout
        while loop.now() < deadline:
            fut = loop.spawn(cs.read())
            try:
                value, _gen = driver.run_until(
                    fut, wall_timeout=max(deadline - loop.now(), 0.1)
                )
            except TimedOut:
                backoff()  # quorum unreachable: back off, re-dial
                continue
            if value and "gateway" in value:
                host, _, port = value["gateway"].rpartition(":")
                return host, int(port)
            backoff()  # quorum up but no leader published yet
        raise TimedOut(f"no leader published by coordinators in {path}")
    finally:
        net.close()


async def _delay_only(loop, seconds: float) -> None:
    await loop.delay(seconds)
