"""Backup agent + restore — the fdbbackup / fdbrestore surface
(fdbclient/FileBackupAgent.actor.cpp; bin equivalents fdbbackup/backup.actor.cpp).

A backup is two artifacts in a container:

  log        the FULL mutation stream from the backup's start boundary,
             written continuously by the BackupWorker (roles/backup.py)
  snapshot   chunked range reads, each chunk = (begin, end, version,
             rows) taken at its own read version (a long snapshot never
             needs one giant MVCC window — same as the reference's
             chunked key-range dumps)

Containers come in two schemes (`backup_container`, the
BackupContainer.actor.cpp URL factory; FDBTPU_BLOB_URL names the
default): `file://<prefix>` is the original DiskQueue pair inside a (sim)
filesystem, and `blob://<name>` stores both artifacts as checksummed
immutable objects in a BlobStore (storage/blobstore.py) — the
off-cluster destination that makes backup a disaster-recovery story: the
uploader retries every request with backoff, a torn multipart upload is
refused at finalize and re-uploaded, and an uploader killed mid-stream
leaves only invisible staging, never a restorable half-object.

Restorable once every chunk's range is covered and the log reaches
max(chunk versions).  restore() applies the chunks, then replays log
mutations — clipped so a mutation only applies where its version exceeds
the covering chunk's version (the chunk already reflects older ones; the
reference restore applies the same version-range filter per range)."""

from __future__ import annotations

import bisect
import os

from ..roles.backup import BackupWorker, decode_log_frame
from ..roles.types import Mutation, MutationType
from ..runtime.core import TaskPriority
from ..runtime.serialize import BinaryReader, BinaryWriter
from ..storage.diskqueue import DiskQueue


class BackupContainer:
    """One backup's files under `prefix` in a (sim) filesystem."""

    def __init__(self, fs, prefix: str, process=None) -> None:
        self.fs = fs
        self.prefix = prefix
        self.log_dq = DiskQueue(fs.open(f"{prefix}-log.dq", process))
        self.snap_dq = DiskQueue(fs.open(f"{prefix}-snapshot.dq", process))

    def log_writer(self):
        """The queue a (re)started backup worker streams into."""
        return self.log_dq

    async def read(self):
        """-> (chunks, log), the async read surface restore() uses (the
        file scheme has no network: this just wraps the sync path)."""
        return read_backup(self)


class BlobBackupContainer:
    """One backup's objects under `<name>/` in a blob store: each log
    sync and each snapshot chunk batch is one immutable checksummed
    object.  `uid` supplies the per-writer nonces — each call must return
    a FRESH value (pass the cluster rng's random_unique_id under
    simulation: object names must be deterministic per seed); the default
    is a process-wide counter, unique per call and deterministic per
    construction order."""

    _uid_seq = 0  # class-wide: default nonces never collide across
                  # containers or replacement writers in one process

    def __init__(self, client, name: str, uid=None) -> None:
        from ..storage.blobstore import BlobQueue

        self.client = client
        self.name = name.strip("/")
        self._uid = uid or self._next_uid
        # the log queue is writer-owned and created per (re)started worker
        # by log_writer() — a verify-only open never allocates one
        self.log_dq = None
        self.snap_dq = BlobQueue(client, f"{self.name}/snapshot", self._uid())

    @classmethod
    def _next_uid(cls) -> str:
        cls._uid_seq += 1
        return f"w{cls._uid_seq:06d}"

    def log_writer(self):
        """A FRESH log queue per (re)started worker: a replacement
        uploader must never share an upload namespace with a dead
        predecessor whose finalize may still be in flight."""
        from ..storage.blobstore import BlobQueue

        self.log_dq = BlobQueue(self.client, f"{self.name}/log", self._uid())
        return self.log_dq

    async def read(self):
        """-> (chunks, log) out of the object store: only COMPLETED
        objects are visible, every body is crc-verified by the client,
        and duplicate log versions (a worker that died between finalize
        and pop re-uploaded its frames) collapse to one."""
        from ..storage.blobstore import BlobQueue

        log_q = self.log_dq or BlobQueue(
            self.client, f"{self.name}/log", self._uid()
        )
        chunks = [_decode_chunk(b) for b in await self.snap_dq.recover()]
        log = [decode_log_frame(b) for b in await log_q.recover()]
        return chunks, _sorted_dedup_log(log)


def backup_container(url: str | None = None, *, fs=None, process=None,
                     blob_client=None, uid=None):
    """The container URL factory (BackupContainer.actor.cpp
    openContainer): `file://<prefix>` (or a bare prefix) opens the
    DiskQueue-backed container inside `fs`; `blob://<name>` opens a
    BlobBackupContainer over the caller's blob client (the simulation
    path); `http://host:port/<name>` dials a BlobStoreServer over real
    sockets.  With no URL, FDBTPU_BLOB_URL names the default."""
    url = url or os.environ.get("FDBTPU_BLOB_URL")
    if not url:
        raise ValueError(
            "no backup container URL (pass one or set FDBTPU_BLOB_URL)"
        )
    if url.startswith("blob://"):
        if blob_client is None:
            raise ValueError("blob:// container needs blob_client=")
        return BlobBackupContainer(blob_client, url[len("blob://"):], uid=uid)
    if url.startswith("http://"):
        from ..storage.blobstore import BlobStoreClient, HttpBlobTransport

        hostport, _, name = url[len("http://"):].partition("/")
        host, _, port = hostport.partition(":")
        client = blob_client or BlobStoreClient(
            HttpBlobTransport(host, int(port or 80))
        )
        return BlobBackupContainer(client, name or "backup", uid=uid)
    prefix = url[len("file://"):] if url.startswith("file://") else url
    if fs is None:
        raise ValueError("file:// container needs fs=")
    return BackupContainer(fs, prefix, process)


def _sorted_dedup_log(log):
    """Version-sorted log with duplicate versions collapsed (a backup
    worker that died between making a frame durable and popping it
    re-reads and re-writes the same frame; applying an ADD twice would
    corrupt the restore)."""
    log.sort(key=lambda e: e[0])
    out = []
    for version, muts in log:
        if out and out[-1][0] == version:
            continue
        out.append((version, muts))
    return out


def _encode_chunk(begin: bytes, end: bytes, version: int, rows) -> bytes:
    w = BinaryWriter().bytes_(begin).bytes_(end).i64(version).u32(len(rows))
    for k, v in rows:
        w.bytes_(k).bytes_(v)
    return w.data()


def _decode_chunk(buf: bytes):
    r = BinaryReader(buf)
    begin, end, version = r.bytes_(), r.bytes_(), r.i64()
    rows = [(r.bytes_(), r.bytes_()) for _ in range(r.u32())]
    return begin, end, version, rows


class BackupAgent:
    """Drives one backup of a RecoverableCluster into a container."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster
        self.worker: BackupWorker | None = None
        self.start_version: int | None = None

    async def start(self, container: BackupContainer) -> int:
        """Begin continuous mutation-log capture; returns the boundary
        version (log complete above it)."""
        cc = self.cluster.controller
        proc = self.cluster.net.create_process("backup-worker")
        # starting below the boundary is safe: the backup tag has no entries
        # before it, so the first peek fast-forwards the cursor
        w = BackupWorker(
            proc, self.cluster.loop, container.log_writer(), start_version=0
        )
        while True:
            vm = await cc.enable_backup(w)
            if vm is not None:
                self.worker = w
                self.start_version = vm
                return vm
            await self.cluster.loop.delay(0.1, TaskPriority.COORDINATION)

    def kill_worker(self) -> None:
        """Power-kill the uploader mid-stream: the worker's task dies at
        its current await point — possibly inside a multipart upload,
        leaving staged parts with no finalize — and its process vanishes.
        The backup tag keeps retaining on the TLogs (pops stop with the
        dead worker), so restart_worker() loses nothing."""
        assert self.worker is not None, "no running backup worker to kill"
        self.worker.stop()
        self.worker.process.kill()
        self.worker = None

    async def restart_worker(self, container) -> None:
        """The uploader-restart path: a killed worker's replacement rejoins
        the backup tag on the current generation (the tag was never
        unregistered, only dark) and re-pulls from its own floor — frames
        the dead worker staged but never finalized are re-uploaded under a
        fresh writer nonce; frames it DID finalize but never popped are
        re-read and deduplicated by version at restore time."""
        from ..roles.backup import BACKUP_TAG
        from ..runtime.coverage import testcov

        cc = self.cluster.controller
        assert BACKUP_TAG in cc.stream_consumers, (
            "restart_worker needs a started backup (the tag registration "
            "outlives the dead worker)"
        )
        proc = self.cluster.net.create_process(
            f"backup-worker-{self.cluster.rng.random_unique_id()[:4]}"
        )
        w = BackupWorker(
            proc, self.cluster.loop, container.log_writer(), start_version=0
        )
        cc.stream_consumers[BACKUP_TAG] = w
        while True:
            gen = cc.generation
            if gen is not None and not cc._recovering:
                break
            await self.cluster.loop.delay(0.1, TaskPriority.COORDINATION)
        cc._wire_stream_consumer(gen, BACKUP_TAG)
        self.worker = w
        testcov("backup.worker_restarted")

    async def snapshot(self, container: BackupContainer, chunk_rows: int = 500) -> int:
        """Chunked full-range dump; returns the max chunk version (the
        backup is restorable once the log passes it)."""
        db = self.cluster.database()
        cursor = b""
        max_v = self.start_version or 0
        from ..keys import key_after

        while True:
            # user range only ([\x00, \xff)): the system keyspace describes
            # THIS cluster's configuration and must not be restored into
            # another (the reference's default backup range)
            tr = db.create_transaction()
            rows = await tr.get_range(cursor, b"\xff", limit=chunk_rows,
                                      snapshot=True)
            v = await tr.get_read_version()
            end = key_after(rows[-1][0]) if len(rows) == chunk_rows else b"\xff"
            container.snap_dq.push(_encode_chunk(cursor, end, v, rows))
            max_v = max(max_v, v)
            if len(rows) < chunk_rows:
                break
            cursor = end
        await container.snap_dq.sync()
        return max_v

    async def wait_backed_up_to(self, version: int, timeout: float = 60.0) -> None:
        from ..runtime.combinators import timeout_error

        await timeout_error(
            self.cluster.loop, self.worker.backed_up.when_at_least(version), timeout
        )

    async def stop(self) -> None:
        try:
            await self.cluster.controller.disable_backup()
        finally:
            if self.worker is not None:
                self.worker.stop()
                self.worker = None


def read_backup(container: BackupContainer):
    """Parse a file container → (chunks, log) for restore/inspection."""
    chunks = [_decode_chunk(b) for b in container.snap_dq.recover()]
    log = [decode_log_frame(b) for b in container.log_dq.recover()]
    return chunks, _sorted_dedup_log(log)


def _restore_plan(chunks, log, target_version: int | None):
    """The ONE clip computation restore() and apply_backup() share: sorted
    snapshot rows plus the log mutations that apply — each clipped so it
    only lands where its version exceeds the covering chunk's version —
    up to target_version.  Returns (rows, ops, target_version)."""
    if not chunks:
        raise ValueError("backup has no snapshot")
    # chunk version step function over keyspace (chunks are disjoint)
    chunks = sorted(chunks, key=lambda c: c[0])
    bounds = [c[0] for c in chunks]
    cvers = [c[2] for c in chunks]
    restorable_from = max(cvers)
    if target_version is None:
        # the log's last FRAME may sit below the newest chunk when no
        # tagged mutation landed in between (coverage advanced through
        # empty versions): the restorable floor still holds
        target_version = max(log[-1][0] if log else 0, restorable_from)
    if target_version < restorable_from:
        raise ValueError(
            f"target {target_version} below newest chunk {restorable_from}"
        )

    def chunk_version_at(key: bytes) -> int:
        i = bisect.bisect_right(bounds, key) - 1
        return cvers[i] if i >= 0 else 0

    rows: list[tuple[bytes, bytes]] = []
    for _b, _e, _v, chunk_rows in chunks:
        rows.extend(chunk_rows)

    ops: list[Mutation] = []
    for version, muts in log:
        if version > target_version:
            break
        for m in muts:
            if m.type == MutationType.CLEAR_RANGE:
                # user range only, split at chunk boundaries; keep parts
                # where the chunk predates the clear
                ce = min(m.value, b"\xff")
                if m.key >= ce:
                    continue
                pts = [m.key] + [b for b in bounds if m.key < b < ce] + [ce]
                for lo, hi in zip(pts, pts[1:]):
                    if version > chunk_version_at(lo):
                        ops.append(Mutation(MutationType.CLEAR_RANGE, lo, hi))
            elif m.key >= b"\xff":
                continue  # system keyspace: not part of the backup
            elif version > chunk_version_at(m.key):
                ops.append(m)
    return rows, ops, target_version


def apply_backup(chunks, log, target_version: int | None = None
                 ) -> dict[bytes, bytes]:
    """Fold a backup into an in-memory key→value dict — the restore
    REFEREE: exactly the state restore() would materialize, without a
    cluster.  Tests and the BlobBackup workload compare this against the
    committed model byte-for-byte."""
    from ..roles.types import apply_atomic

    rows, ops, _tv = _restore_plan(chunks, log, target_version)
    state: dict[bytes, bytes] = dict(rows)
    for m in ops:
        if m.type == MutationType.SET_VALUE:
            state[m.key] = m.value
        elif m.type == MutationType.CLEAR_RANGE:
            for k in [k for k in state if m.key <= k < m.value]:
                del state[k]
        else:
            state[m.key] = apply_atomic(m.type, state.get(m.key), m.value)
    return state


async def restore(db, container, target_version: int | None = None,
                  batch: int = 300) -> int:
    """Restore a backup into an (empty-range) database.  Applies snapshot
    chunks, then replays the mutation log where version > the covering
    chunk's version, up to target_version (default: everything captured).
    Works against either container scheme (the blob path reads only
    completed, checksum-verified objects — a torn upload is refused, so
    it can never be restored).  Returns the version the restored state
    corresponds to."""
    chunks, log = await container.read()
    rows, ops, target_version = _restore_plan(chunks, log, target_version)

    # 1. snapshot chunks, batched transactions
    for i in range(0, len(rows), batch):
        part = rows[i : i + batch]

        async def fn(tr, part=part):
            for k, v in part:
                tr.set(k, v)

        await db.run(fn)

    # 2. log replay, clipped per chunk version
    for i in range(0, len(ops), batch):
        part = ops[i : i + batch]

        async def fn(tr, part=part):
            for m in part:
                if m.type == MutationType.SET_VALUE:
                    tr.set(m.key, m.value)
                elif m.type == MutationType.CLEAR_RANGE:
                    tr.clear_range(m.key, m.value)
                else:
                    tr.atomic_op(m.type, m.key, m.value)

        await db.run(fn)
    return target_version
