"""Backup agent + restore — the fdbbackup / fdbrestore surface
(fdbclient/FileBackupAgent.actor.cpp; bin equivalents fdbbackup/backup.actor.cpp).

A backup is two artifacts in a container (a prefix inside a filesystem):

  log.dq        the FULL mutation stream from the backup's start boundary,
                written continuously by the BackupWorker (roles/backup.py)
  snapshot.dq   chunked range reads, each chunk = (begin, end, version,
                rows) taken at its own read version (a long snapshot never
                needs one giant MVCC window — same as the reference's
                chunked key-range dumps)

Restorable once every chunk's range is covered and the log reaches
max(chunk versions).  restore() applies the chunks, then replays log
mutations — clipped so a mutation only applies where its version exceeds
the covering chunk's version (the chunk already reflects older ones; the
reference restore applies the same version-range filter per range)."""

from __future__ import annotations

import bisect

from ..roles.backup import BackupWorker, decode_log_frame
from ..roles.types import Mutation, MutationType
from ..runtime.core import TaskPriority
from ..runtime.serialize import BinaryReader, BinaryWriter
from ..storage.diskqueue import DiskQueue


class BackupContainer:
    """One backup's files under `prefix` in a (sim) filesystem."""

    def __init__(self, fs, prefix: str, process=None) -> None:
        self.fs = fs
        self.prefix = prefix
        self.log_dq = DiskQueue(fs.open(f"{prefix}-log.dq", process))
        self.snap_dq = DiskQueue(fs.open(f"{prefix}-snapshot.dq", process))


def _encode_chunk(begin: bytes, end: bytes, version: int, rows) -> bytes:
    w = BinaryWriter().bytes_(begin).bytes_(end).i64(version).u32(len(rows))
    for k, v in rows:
        w.bytes_(k).bytes_(v)
    return w.data()


def _decode_chunk(buf: bytes):
    r = BinaryReader(buf)
    begin, end, version = r.bytes_(), r.bytes_(), r.i64()
    rows = [(r.bytes_(), r.bytes_()) for _ in range(r.u32())]
    return begin, end, version, rows


class BackupAgent:
    """Drives one backup of a RecoverableCluster into a container."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster
        self.worker: BackupWorker | None = None
        self.start_version: int | None = None

    async def start(self, container: BackupContainer) -> int:
        """Begin continuous mutation-log capture; returns the boundary
        version (log complete above it)."""
        cc = self.cluster.controller
        proc = self.cluster.net.create_process("backup-worker")
        # starting below the boundary is safe: the backup tag has no entries
        # before it, so the first peek fast-forwards the cursor
        w = BackupWorker(proc, self.cluster.loop, container.log_dq, start_version=0)
        while True:
            vm = await cc.enable_backup(w)
            if vm is not None:
                self.worker = w
                self.start_version = vm
                return vm
            await self.cluster.loop.delay(0.1, TaskPriority.COORDINATION)

    async def snapshot(self, container: BackupContainer, chunk_rows: int = 500) -> int:
        """Chunked full-range dump; returns the max chunk version (the
        backup is restorable once the log passes it)."""
        db = self.cluster.database()
        cursor = b""
        max_v = self.start_version or 0
        from ..keys import key_after

        while True:
            # user range only ([\x00, \xff)): the system keyspace describes
            # THIS cluster's configuration and must not be restored into
            # another (the reference's default backup range)
            tr = db.create_transaction()
            rows = await tr.get_range(cursor, b"\xff", limit=chunk_rows,
                                      snapshot=True)
            v = await tr.get_read_version()
            end = key_after(rows[-1][0]) if len(rows) == chunk_rows else b"\xff"
            container.snap_dq.push(_encode_chunk(cursor, end, v, rows))
            max_v = max(max_v, v)
            if len(rows) < chunk_rows:
                break
            cursor = end
        await container.snap_dq.sync()
        return max_v

    async def wait_backed_up_to(self, version: int, timeout: float = 60.0) -> None:
        from ..runtime.combinators import timeout_error

        await timeout_error(
            self.cluster.loop, self.worker.backed_up.when_at_least(version), timeout
        )

    async def stop(self) -> None:
        try:
            await self.cluster.controller.disable_backup()
        finally:
            if self.worker is not None:
                self.worker.stop()
                self.worker = None


def read_backup(container: BackupContainer):
    """Parse a container → (chunks, log) for restore/inspection."""
    chunks = [_decode_chunk(b) for b in container.snap_dq.recover()]
    log = [decode_log_frame(b) for b in container.log_dq.recover()]
    log.sort(key=lambda e: e[0])
    return chunks, log


async def restore(db, container: BackupContainer, target_version: int | None = None,
                  batch: int = 300) -> int:
    """Restore a backup into an (empty-range) database.  Applies snapshot
    chunks, then replays the mutation log where version > the covering
    chunk's version, up to target_version (default: everything captured).
    Returns the version the restored state corresponds to."""
    chunks, log = read_backup(container)
    if not chunks:
        raise ValueError("backup has no snapshot")
    # chunk version step function over keyspace (chunks are disjoint)
    chunks.sort(key=lambda c: c[0])
    bounds = [c[0] for c in chunks]
    cvers = [c[2] for c in chunks]
    restorable_from = max(cvers)
    if target_version is None:
        target_version = log[-1][0] if log else restorable_from
    if target_version < restorable_from:
        raise ValueError(
            f"target {target_version} below newest chunk {restorable_from}"
        )

    def chunk_version_at(key: bytes) -> int:
        i = bisect.bisect_right(bounds, key) - 1
        return cvers[i] if i >= 0 else 0

    # 1. snapshot chunks, batched transactions
    pending: list[tuple[bytes, bytes]] = []
    for _b, _e, _v, rows in chunks:
        pending.extend(rows)
    for i in range(0, len(pending), batch):
        part = pending[i : i + batch]

        async def fn(tr, part=part):
            for k, v in part:
                tr.set(k, v)

        await db.run(fn)

    # 2. log replay, clipped per chunk version
    ops: list[Mutation] = []
    for version, muts in log:
        if version > target_version:
            break
        for m in muts:
            if m.type == MutationType.CLEAR_RANGE:
                # user range only, split at chunk boundaries; keep parts
                # where the chunk predates the clear
                ce = min(m.value, b"\xff")
                if m.key >= ce:
                    continue
                pts = [m.key] + [b for b in bounds if m.key < b < ce] + [ce]
                for lo, hi in zip(pts, pts[1:]):
                    if version > chunk_version_at(lo):
                        ops.append(Mutation(MutationType.CLEAR_RANGE, lo, hi))
            elif m.key >= b"\xff":
                continue  # system keyspace: not part of the backup
            elif version > chunk_version_at(m.key):
                ops.append(m)
    for i in range(0, len(ops), batch):
        part = ops[i : i + batch]

        async def fn(tr, part=part):
            for m in part:
                if m.type == MutationType.SET_VALUE:
                    tr.set(m.key, m.value)
                elif m.type == MutationType.CLEAR_RANGE:
                    tr.clear_range(m.key, m.value)
                else:
                    tr.atomic_op(m.type, m.key, m.value)

        await db.run(fn)
    return target_version
