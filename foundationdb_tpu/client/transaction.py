"""Client transaction API — the NativeAPI analog (fdbclient/NativeAPI.actor.cpp).

`Database` is the connection handle; `Transaction` implements the FDB
transaction model: snapshot reads at a GRV-acquired read version
(getReadVersion :2821), buffered writes, conflict ranges accumulated per
read/write, OCC commit via the proxy (tryCommit :2412), and the retry loop
(`Database.run`, the `fdb.transactional` analog: on_error backoff + full
retry on NotCommitted / TransactionTooOld).

Reads route to storage servers by key partition (the client's location
cache, getKeyLocation_internal :1085 — here a static map handed out by the
cluster; invalidation/refresh arrives with data distribution).
"""

from __future__ import annotations

from ..roles.proxy import KeyPartitionMap
from ..roles.types import (
    CLIENT_KEYSPACE_END,
    CommitReply,
    CommitResult,
    CommitTransactionRequest,
    CommitUnknownResult,
    FutureVersion,
    GetKeyRequest,
    GetKeyValuesRequest,
    GetReadVersionRequest,
    GetValueRequest,
    KeySelector,
    Mutation,
    MutationType,
    NotCommitted,
    TransactionTooOld,
    Version,
)
from ..rpc.stream import RequestStreamRef
from ..runtime.core import (
    ActorCancelled,
    BrokenPromise,
    DeterministicRandom,
    EventLoop,
    Promise,
    TimedOut,
)
from ..runtime.trace import g_trace_batch
from ..keys import key_after
from ..runtime.coverage import testcov

# errors a client retry loop may transparently retry (the onError set,
# NativeAPI.actor.cpp:2543 — not_committed / transaction_too_old /
# future_version / commit_unknown_result / proxy-unreachable timeouts /
# broken-promise connection resets)
RETRYABLE_ERRORS = (
    NotCommitted,
    TransactionTooOld,
    FutureVersion,
    CommitUnknownResult,
    TimedOut,
    BrokenPromise,
)


def selector_conflict_range(
    sel: KeySelector, resolved: bytes
) -> tuple[bytes, bytes] | None:
    """The read-conflict range a getKey adds (NativeAPI.actor.cpp
    getKeyAndConflictRange): the span whose contents DETERMINED the
    resolution — any write inside it could move the resolved position.
    Backward selectors depend on [resolved, anchor), forward ones on
    (anchor, resolved]; or_equal widens the anchor side to include the
    anchor key itself.  None when the span is empty."""
    if sel.offset <= 0:
        b, e = resolved, (key_after(sel.key) if sel.or_equal else sel.key)
    else:
        b, e = (key_after(sel.key) if sel.or_equal else sel.key), key_after(resolved)
    return (b, e) if b < e else None


def _intersect_ranges(
    a: list[tuple[bytes, bytes]], b: list[tuple[bytes, bytes]]
) -> tuple[bytes, bytes] | None:
    """First non-empty intersection of any range in `a` with any in `b`."""
    for ab, ae in a:
        for bb, be in b:
            lo, hi = max(ab, bb), min(ae, be)
            if lo < hi:
                return lo, hi
    return None


class ClusterView:
    """The client's window onto the current cluster generation — the
    MonitorLeader/cluster-file analog.  The control plane mutates these
    attributes on recovery; every Transaction reads them per call, so
    clients follow failovers without restarting.

    `grvs`/`commits` hold one ref per proxy; clients spread load across
    them (the reference load-balances MasterProxyInterface the same way)."""

    def __init__(
        self,
        grv_refs: list[RequestStreamRef] | RequestStreamRef | None,
        commit_refs: list[RequestStreamRef] | RequestStreamRef | None,
        storage_map: KeyPartitionMap,  # members: {"getvalue": ref, "getkeyvalues": ref}
        epoch: int = 0,
    ) -> None:
        def as_list(x):
            return x if isinstance(x, list) or x is None else [x]

        self.grvs = as_list(grv_refs)
        self.commits = as_list(commit_refs)
        self.smap = storage_map
        self.epoch = epoch
        # special key space handlers (SpecialKeySpace.actor.cpp): module
        # reads under \xff\xff, e.g. the status-client path.  special_keys
        # answers exact-key gets; special_ranges is [(prefix, handler)] for
        # module RANGE reads (handler() -> [(key, value)] rows)
        self.special_keys: dict[bytes, object] = {}
        self.special_ranges: list[tuple[bytes, object]] = []


class QueueModel:
    """Per-replica latency/penalty model for read load-balancing
    (fdbrpc/QueueModel.h + LoadBalance.actor.h:159): smoothed reply latency
    plus an in-flight count per endpoint; picks the better of two random
    candidates (the reference's alternatives comparison), and a broken
    endpoint carries a decaying penalty so retries steer away from it."""

    def __init__(self, clock) -> None:
        self._clock = clock
        # endpoint key -> [smoothed_latency, inflight, penalty_until, last_t]
        self._stats: dict = {}
        # cluster-wide FailureMonitor (rpc/failmon.py), wired by Database
        # when the view carries one: pick() skips replicas the cluster
        # already knows are down instead of paying a timeout to rediscover
        # it (LoadBalance.actor.h consulting IFailureMonitor::getState)
        self.failmon = None

    def _key(self, ref) -> tuple:
        ep = ref.endpoint
        return (ep.address, ep.token)

    def _entry(self, ref):
        e = self._stats.get(self._key(ref))
        if e is None:
            if len(self._stats) > 4096:
                # endpoints churn with every recovery: drop the stalest
                stale = min(self._stats, key=lambda k: self._stats[k][3])
                del self._stats[stale]
            e = self._stats[self._key(ref)] = [0.001, 0, 0.0, self._clock()]
        return e

    def _score(self, ref) -> float:
        lat, inflight, penalty_until, last_t = self._entry(ref)
        now = self._clock()
        p = 10.0 if now < penalty_until else 0.0
        if now - last_t > 2.0:
            # a losing replica's estimate goes stale (it is never picked,
            # so never refreshed): forget its history so it gets re-probed
            # — the role of the reference LoadBalance's second requests
            lat = 0.001
        return lat * (1 + inflight) + p

    def pick(self, rng, members: list, opkey: str):
        if self.failmon is not None and len(members) > 1:
            live = [
                m for m in members
                if not self.failmon.is_failed(m[opkey].endpoint.address)
            ]
            if live:  # all-failed: fall through and probe anyway
                members = live
        if len(members) == 1:
            return members[0][opkey]
        i = rng.random_int(0, len(members))
        j = (i + 1 + rng.random_int(0, len(members) - 1)) % len(members)
        ra, rb = members[i][opkey], members[j][opkey]
        return ra if self._score(ra) <= self._score(rb) else rb

    def on_start(self, ref) -> None:
        self._entry(ref)[1] += 1

    def on_reply(self, ref, latency: float) -> None:
        e = self._entry(ref)
        e[0] += (latency - e[0]) * 0.2
        e[1] = max(e[1] - 1, 0)
        e[3] = self._clock()

    def on_abandon(self, ref) -> None:
        """Timeout/cancel: no reply was observed — never feed the elapsed
        wait into the latency estimate (it measures the caller, not the
        replica)."""
        self._entry(ref)[1] = max(self._entry(ref)[1] - 1, 0)

    def on_broken(self, ref) -> None:
        e = self._entry(ref)
        e[1] = max(e[1] - 1, 0)
        e[2] = self._clock() + 1.0  # steer away while it is likely dead
        e[3] = self._clock()


class Database:
    def __init__(
        self,
        loop: EventLoop,
        view: ClusterView,
        rng: DeterministicRandom,
        client_knobs=None,
    ) -> None:
        from ..runtime.knobs import ClientKnobs

        self.loop = loop
        self.view = view
        self.knobs = client_knobs or ClientKnobs()
        self._rng = rng.split()
        self._qm = QueueModel(loop.now)
        self._qm.failmon = getattr(view, "failure_monitor", None)
        # fraction of transactions given a pipeline-timeline debug ID
        # (g_traceBatch; the reference samples via CLIENT_KNOBS->
        # *_DEBUG_TRANSACTION_RATE)
        self.debug_sample_rate = 0.0
        # RYW SnapshotCache counters, aggregated across every transaction
        # this handle creates (client/snapshot_cache.py); surfaced in
        # cluster_status and the periodic ClientMetrics trace event
        from .snapshot_cache import CacheStats

        self.cache_stats = CacheStats()
        self._metrics_emitter = None

    def start_metrics(self, trace, interval: float, process=None):
        """Periodic ClientMetrics emission — the client-side slice of the
        `*Metrics` plane (the reference's TransactionMetrics): RYW cache
        hit/miss/insert/eviction rates plus the live cache-byte gauge."""
        from ..runtime.trace import spawn_role_metrics

        if self._metrics_emitter is not None:
            self._metrics_emitter.cancel()

        def fields() -> dict:
            r = self.cache_stats.counters.rates(self.loop.now())
            snap = self.cache_stats.snapshot()
            return {
                "CacheHitsPerSec": r.get("cache_hits", 0.0),
                "CacheMissesPerSec": r.get("cache_misses", 0.0),
                "CacheInsertsPerSec": r.get("cache_inserts", 0.0),
                "CacheEvictionsPerSec": r.get("cache_evictions", 0.0),
                "SelectorReadsPerSec": r.get("selector_reads", 0.0),
                "CacheBytes": snap["bytes"],
                "CachedTransactions": snap["transactions"],
            }

        self._metrics_emitter = spawn_role_metrics(
            self.loop, process, trace, "ClientMetrics", fields, interval,
        )
        return self._metrics_emitter

    @property
    def _grv(self) -> RequestStreamRef:
        return self._rng.random_choice(self.view.grvs)

    @property
    def _commit(self) -> RequestStreamRef:
        return self._rng.random_choice(self.view.commits)

    @property
    def _smap(self) -> KeyPartitionMap:
        return self.view.smap

    def create_transaction(self) -> "Transaction":
        tr = Transaction(self)
        if self.debug_sample_rate > 0 and self._rng.random() < self.debug_sample_rate:
            tr.debug_id = self._rng.random_unique_id()[:12]
            g_trace_batch.add("NativeAPI.createTransaction", tr.debug_id)
        return tr

    def create_ryw_transaction(self):
        """A read-your-writes transaction (the reference's default client
        surface, fdbclient/ReadYourWrites.actor.cpp)."""
        from .ryw import ReadYourWritesTransaction

        return ReadYourWritesTransaction(self)

    async def watch(self, key: bytes):
        """Future resolving when `key`'s value changes from its current
        value (fdbclient watch semantics: register against the storage
        server owning the key)."""
        from ..roles.types import WatchValueRequest

        tr = self.create_transaction()
        current = await tr.get(key, snapshot=True)
        v = await tr.get_read_version()

        async def waiter():
            # loadBalance over the shard's team: a dead replica answers
            # BrokenPromise, so re-register against another one
            while True:
                refs = self._rng.random_choice(self._smap.member_for_key(key))
                try:
                    return await refs["watch"].get_reply(
                        WatchValueRequest(key, current, v)
                    )
                except BrokenPromise:
                    await self.loop.delay(0.05)

        return self.loop.spawn(waiter())

    async def run(self, fn, max_retries: int = 50, ryw: bool = True):
        """Retry loop (fdb.transactional): run fn(tr), commit; on retryable
        errors `tr.on_error` backs off — and for CommitUnknownResult first
        fences the in-flight original with the dummy-transaction dance
        (NativeAPI.actor.cpp:2482-2502) — then the loop starts over with a
        fresh read version.

        The fence only prevents the zombie-commit race (the original landing
        AFTER the retry's reads); a CommitUnknownResult retry can still
        re-apply fn if the original committed — safe only for idempotent or
        self-verifying transactions, the same contract as the reference.

        Transactions are read-your-writes by default (the reference's client
        surface); pass ryw=False for the raw snapshot-read flavor."""
        tr = self.create_ryw_transaction() if ryw else self.create_transaction()
        for _attempt in range(max_retries):
            try:
                result = await fn(tr)
                await tr.commit()
                return result
            except RETRYABLE_ERRORS as e:
                await tr.on_error(e)
        raise NotCommitted(f"transaction failed after {max_retries} retries")


class Transaction:
    def __init__(self, db: Database) -> None:
        self.db = db
        self._read_version: Version | None = None
        # single-flight GRV: concurrent first reads share ONE in-flight
        # fetch (the reference caches a Future<Version>, not a value —
        # NativeAPI's readVersion), or two racing reads could land in
        # different proxy batches and observe DIFFERENT snapshots
        self._grv_fetch = None
        self._mutations: list[Mutation] = []
        self._read_ranges: list[tuple[bytes, bytes]] = []
        self._write_ranges: list[tuple[bytes, bytes]] = []
        self.committed_version: Version | None = None
        self._backoff = db.knobs.DEFAULT_BACKOFF  # carried across resets
        self.debug_id: str | None = None  # set by sampled create_transaction
        self._priority = 1  # TransactionPriority.DEFAULT
        self._causal_write_risky = False
        self._lock_aware = False

    def set_option(self, option: bytes, value: bytes | None = None) -> None:
        """Transaction options (fdb_transaction_set_option; the generated
        surface the reference's vexillographer emits).  Supported:

          priority_batch              yield to other traffic under load
          priority_system_immediate   bypass ratekeeper admission
          causal_write_risky          skip the self-conflict ranges that
                                      make the unknown-result fence certain
                                      (faster commits, weaker retry safety)
          lock_aware                  commit through a locked database
                                      (ManagementAPI lock/unlock)
          debug_transaction_identifier  value = id; join pipeline timelines
        """
        from ..roles.types import PRIORITY_BATCH, PRIORITY_IMMEDIATE

        if option == b"priority_batch":
            self._priority = PRIORITY_BATCH
        elif option == b"priority_system_immediate":
            self._priority = PRIORITY_IMMEDIATE
        elif option == b"causal_write_risky":
            self._causal_write_risky = True
        elif option == b"lock_aware":
            self._lock_aware = True
        elif option == b"debug_transaction_identifier":
            if not value:
                raise ValueError("debug_transaction_identifier needs a value")
            self.debug_id = value.decode()
        else:
            raise ValueError(f"unknown transaction option {option!r}")

    def reset(self) -> None:
        """Clear all transaction state for a retry (fresh read version,
        empty mutation/conflict sets); the retry backoff is preserved."""
        self._read_version = None
        self._grv_fetch = None
        self._mutations = []
        self._read_ranges = []
        self._write_ranges = []
        self.committed_version = None

    async def on_error(self, e: BaseException) -> None:
        """The reference's tr.onError contract (NativeAPI.actor.cpp:2543):
        for a retryable error, back off and reset this transaction so the
        caller can re-run its body.  Non-retryable errors re-raise.

        For CommitUnknownResult the in-flight original commit is first
        FENCED (:2482-2502): commit a dummy transaction whose write set
        intersects this transaction's read conflict ranges.  Once the dummy
        commits, the original — whose read snapshot predates it — can never
        commit afterwards, so the retry cannot race a zombie commit into a
        double-apply.  The intersection always exists because commit()
        makes every transaction self-conflicting when its read and write
        sets are disjoint — UNLESS the causal_write_risky option disabled
        that, in which case the fence is skipped and a retried unknown-
        result commit may double-apply (the option's documented trade)."""
        if not isinstance(e, RETRYABLE_ERRORS):
            raise e
        if isinstance(e, CommitUnknownResult) and self._write_ranges:
            fence = _intersect_ranges(self._write_ranges, self._read_ranges)
            if fence is not None:
                testcov("client.unknown_result_fence")
                await self._commit_fence(fence[0])
        await self.db.loop.delay(self._backoff * (0.5 + self.db._rng.random()))
        self._backoff = min(self._backoff * 2, self.db.knobs.MAX_BACKOFF)
        self.reset()

    async def _commit_fence(self, key: bytes) -> None:
        """Commit a dummy transaction conflicting with the original
        (commitDummyTransaction, NativeAPI.actor.cpp:2380): read+write
        conflict ranges on one key, no mutations.  Retries until it lands;
        a dummy's own unknown result is safe to retry (it is idempotent)."""
        for _ in range(50):
            dummy = self.db.create_transaction()
            # always lock-aware (the reference's commitDummyTransaction sets
            # LOCK_AWARE unconditionally): the fence must land even if the
            # database was locked between the unknown commit and the retry —
            # it writes nothing, it only settles the original's outcome
            dummy._lock_aware = True
            dummy.add_read_conflict_range(key, key_after(key))
            dummy.add_write_conflict_range(key, key_after(key))
            try:
                await dummy.commit()
                return
            except RETRYABLE_ERRORS:
                await self.db.loop.delay(
                    self._backoff * (0.5 + self.db._rng.random())
                )
        raise CommitUnknownResult("fence transaction could not commit")

    async def _reply_rerouted(self, pick_ref, payload, timeout: float | None = None):
        """get_reply with fast re-route: a BrokenPromise (dead endpoint —
        the connection-reset analog) retries immediately against a freshly
        picked ref (the view is re-read, so a recovery's rewire takes
        effect), the reference's loadBalance/alternatives loop.  Only the
        overall deadline surfaces, as TimedOut."""
        loop = self.db.loop
        qm = self.db._qm
        if timeout is None:
            timeout = self.db.knobs.REQUEST_TIMEOUT
        deadline = loop.now() + timeout
        while True:
            remaining = deadline - loop.now()
            if remaining <= 0:
                raise TimedOut(f"timed out after {timeout}s")
            ref = pick_ref()
            qm.on_start(ref)
            t0 = loop.now()
            try:
                reply = await ref.get_reply(payload, timeout=remaining)
                qm.on_reply(ref, loop.now() - t0)
                return reply
            except BrokenPromise:
                qm.on_broken(ref)
                await loop.delay(
                    min(self.db.knobs.REROUTE_DELAY,
                        max(deadline - loop.now(), 0.001))
                )
            except (TimedOut, ActorCancelled):
                qm.on_abandon(ref)  # no reply observed: not a latency sample
                raise
            except Exception:
                qm.on_reply(ref, loop.now() - t0)  # an error IS a reply
                raise

    # -- read version -------------------------------------------------------
    async def _fetch_read_version(self) -> Version:
        g_trace_batch.add(
            "NativeAPI.getConsistentReadVersion.Before", self.debug_id
        )
        reply = await self._reply_rerouted(
            lambda: self.db._grv,
            GetReadVersionRequest(debug_id=self.debug_id,
                                  priority=self._priority),
        )
        g_trace_batch.add(
            "NativeAPI.getConsistentReadVersion.After", self.debug_id
        )
        return reply.version

    async def get_read_version(self) -> Version:
        # take ownership of the fetch BEFORE suspending: two reads racing
        # the first GRV must share ONE request, or they can land in
        # different proxy batches and pin DIFFERENT snapshots to one
        # transaction (flowcheck check-then-act audit; regression-pinned by
        # test_concurrent_first_reads_share_one_read_version).  The leader
        # fetches inline (scheduling-identical to the sequential path);
        # followers await its future.
        while self._read_version is None:
            # flowlint: ok stale-read-across-await (deliberate: the handler inspects the OUTCOME of the very future it awaited, not the current fetch)
            fut = self._grv_fetch
            if fut is not None:
                # follower: share the in-flight fetch.  A LEADER failure is
                # not ours to surface — re-lead a fresh fetch under our own
                # deadline; only our own cancellation propagates.
                try:
                    await fut
                except ActorCancelled:
                    if fut.done() and fut.exception() is not None:
                        continue  # the leader was cancelled: re-lead
                    raise         # we ourselves were cancelled
                except Exception:  # noqa: BLE001 — leader's fetch failed
                    continue      # re-lead (shielded by the handler above)
                continue  # leader filled _read_version
            p = Promise()
            self._grv_fetch = p.future
            try:
                v = await self._fetch_read_version()
            except BaseException as e:
                if self._grv_fetch is p.future:
                    self._grv_fetch = None  # next caller leads a fresh fetch
                p.fail(e)
                raise
            # publish only while still owning the fetch: a reset() during
            # the RPC cleared the slot and a NEW leader may be in flight —
            # stamping the pre-reset version here would pin the RETRIED
            # transaction to a stale snapshot.  Disowned: wake followers
            # and loop — they (and we) follow the new fetch.
            if self._grv_fetch is p.future:
                self._read_version = v
            p.send(v)
        return self._read_version

    # -- reads --------------------------------------------------------------
    async def get(self, key: bytes, snapshot: bool = False) -> bytes | None:
        if key.startswith(b"\xff\xff"):
            # special key space (fdbclient/SpecialKeySpace.actor.cpp): reads
            # under \xff\xff are answered by module handlers, not storage —
            # e.g. \xff\xff/status/json is the status-client fetch path
            handler = self.db.view.special_keys.get(key)
            if handler is not None:
                return handler()
            # range modules answer exact gets too (SpecialKeySpace: a get
            # inside a module's range resolves against its rows)
            for prefix, rh in self.db.view.special_ranges:
                if key.startswith(prefix):
                    for k, v in rh():
                        if k == key:
                            return v
                    return None
            return None
        v = await self.get_read_version()
        # loadBalance (fdbrpc/LoadBalance.actor.h:159): pick a random replica
        # of the shard's team per attempt; _reply_rerouted re-picks on a
        # dead endpoint, so reads fail over to the surviving replicas
        g_trace_batch.add("NativeAPI.getValue.Before", self.debug_id)
        reply = await self._reply_rerouted(
            lambda: self.db._qm.pick(
                self.db._rng, self.db._smap.member_for_key(key), "getvalue"
            ),
            GetValueRequest(key, v, debug_id=self.debug_id),
        )
        g_trace_batch.add("NativeAPI.getValue.After", self.debug_id)
        if not snapshot:
            self._read_ranges.append((key, key_after(key)))
        return reply.value

    # -- key selectors (NativeAPI.actor.cpp getKey) --------------------------
    def _selector_route(self, sel: KeySelector) -> tuple[int, bytes, bytes]:
        """(member index, shard begin, shard end) for one resolution step.
        A backward selector anchored EXACTLY on a shard boundary routes to
        the shard on the LEFT (the reference's Reverse getKeyLocation):
        every key it can resolve to lives there."""
        smap = self.db._smap
        idx = smap.position_for_key(sel.key)
        if sel.is_backward and idx > 0 and sel.key == smap.splits[idx - 1]:
            idx -= 1
        mb = smap.splits[idx - 1] if idx > 0 else b""
        me = smap.splits[idx] if idx < len(smap.splits) else CLIENT_KEYSPACE_END
        return idx, mb, min(me, CLIENT_KEYSPACE_END)

    async def get_key(self, selector: KeySelector, snapshot: bool = False) -> bytes:
        """Resolve a KeySelector to an actual key (fdb_transaction_get_key).
        Resolution happens SERVER-side: each step asks the shard the
        selector currently points into; an offset stepping past the shard
        boundary comes back as an updated selector for the next shard.  A
        position before the first key clamps to b""; past the last user
        key clamps to CLIENT_KEYSPACE_END (b"\\xff") — offset overflow
        yields the boundary, never an error (docs/API.md)."""
        if not isinstance(selector, KeySelector):
            raise TypeError("get_key takes a KeySelector")
        if selector.key.startswith(b"\xff\xff"):
            raise ValueError("key selectors are not supported under \\xff\\xff")
        v = await self.get_read_version()
        sel = selector
        g_trace_batch.add("NativeAPI.getKey.Before", self.debug_id)
        while True:
            # boundary clamps FIRST (the reference's allKeys.begin/end checks)
            if sel.key >= CLIENT_KEYSPACE_END:
                if sel.offset > 0:
                    rep = CLIENT_KEYSPACE_END
                    break
                sel = KeySelector(CLIENT_KEYSPACE_END, False, sel.offset)
            if sel.key == b"" and sel.offset <= 0:
                rep = b""
                break
            idx, mb, me = self._selector_route(sel)
            reply = await self._reply_rerouted(
                lambda idx=idx: self.db._qm.pick(
                    self.db._rng, self.db._smap.members[idx], "getkey"
                ),
                GetKeyRequest(sel, v, mb, me, debug_id=self.debug_id),
            )
            sel = reply.sel
            if sel.is_resolved:
                rep = sel.key
                break
        g_trace_batch.add("NativeAPI.getKey.After", self.debug_id)
        if not snapshot:
            cr = selector_conflict_range(selector, rep)
            if cr is not None:
                self._read_ranges.append(cr)
        return rep

    async def get_range(
        self,
        begin: bytes | KeySelector,
        end: bytes | KeySelector,
        limit: int = 10000,
        snapshot: bool = False,
    ) -> list[tuple[bytes, bytes]]:
        if isinstance(begin, KeySelector) or isinstance(end, KeySelector):
            # selector endpoints resolve server-side first (each adds its
            # own narrow resolution conflict range); the data read then
            # proceeds over the resolved window
            b = begin if isinstance(begin, bytes) else await self.get_key(
                begin, snapshot=snapshot
            )
            e = end if isinstance(end, bytes) else await self.get_key(
                end, snapshot=snapshot
            )
            if b >= e:
                return []
            return await self.get_range(b, e, limit=limit, snapshot=snapshot)
        if begin.startswith(b"\xff\xff"):
            # special-key-space MODULE range read (SpecialKeySpace.actor.cpp:
            # `\xff\xff/<module>/...` ranges answered by handlers, not
            # storage — e.g. \xff\xff/keyservers/, \xff\xff/excluded/)
            out = []
            for prefix, handler in self.db.view.special_ranges:
                if begin < prefix + b"\xff" and prefix < end:
                    out.extend(
                        (k, v) for k, v in handler()
                        if begin <= k < end
                    )
            return sorted(out)[:limit]
        v = await self.get_read_version()
        out: list[tuple[bytes, bytes]] = []
        smap = self.db._smap
        # walk shards left to right (the client iterates locations :1228)
        for idx in range(len(smap.members)):
            clip = smap.clip_to_member(idx, begin, end)
            if clip is None:
                continue
            b, e = clip
            reply = await self._reply_rerouted(
                lambda idx=idx: self.db._qm.pick(
                    self.db._rng, self.db._smap.members[idx], "getkeyvalues"
                ),
                GetKeyValuesRequest(b, e, v, limit - len(out)),
            )
            out.extend(reply.data)
            if len(out) >= limit:
                break
        if not snapshot:
            self._read_ranges.append((begin, end))
        return out[:limit]

    # -- writes -------------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        self._mutations.append(Mutation(MutationType.SET_VALUE, key, value))
        self._write_ranges.append((key, key_after(key)))

    def clear(self, key: bytes) -> None:
        self.clear_range(key, key_after(key))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self._mutations.append(Mutation(MutationType.CLEAR_RANGE, begin, end))
        self._write_ranges.append((begin, end))

    def atomic_op(self, op: MutationType, key: bytes, operand: bytes) -> None:
        # versionstamped placeholders are validated HERE, at the API
        # boundary: the proxy must never see a malformed offset it would
        # have to fail mid-batch (it still guards, defense in depth)
        if op == MutationType.SET_VERSIONSTAMPED_KEY:
            from ..roles.types import VERSIONSTAMP_LEN

            off = int.from_bytes(key[-4:], "little")
            if len(key) < 14 or off + VERSIONSTAMP_LEN > len(key) - 4:
                raise ValueError(f"versionstamp offset {off} out of range")
        elif op == MutationType.SET_VERSIONSTAMPED_VALUE:
            from ..roles.types import VERSIONSTAMP_LEN

            off = int.from_bytes(operand[-4:], "little")
            if len(operand) < 14 or off + VERSIONSTAMP_LEN > len(operand) - 4:
                raise ValueError(f"versionstamp offset {off} out of range")
        self._mutations.append(Mutation(op, key, operand))
        self._write_ranges.append((key, key_after(key)))

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        self._read_ranges.append((begin, end))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        self._write_ranges.append((begin, end))

    # -- commit -------------------------------------------------------------
    async def commit(self) -> Version:
        if not self._mutations and not self._write_ranges:
            self.committed_version = self._read_version or 0
            return self.committed_version  # read-only: nothing to commit
        v = await self.get_read_version()
        if (
            not self._causal_write_risky
            and _intersect_ranges(self._write_ranges, self._read_ranges) is None
        ):
            # make the transaction self-conflicting (the reference's
            # makeSelfConflicting under !causalWriteRisky): gives on_error's
            # unknown-result fence a range that aborts the in-flight
            # original for certain.  A unique key adds no spurious
            # conflicts with other transactions.
            sc = b"\xff/SC/" + self.db._rng.random_unique_id().encode()
            self._read_ranges.append((sc, key_after(sc)))
            self._write_ranges.append((sc, key_after(sc)))
        req = CommitTransactionRequest(
            read_snapshot=v,
            read_conflict_ranges=list(self._read_ranges),
            write_conflict_ranges=list(self._write_ranges),
            mutations=list(self._mutations),
            debug_id=self.debug_id,
            lock_aware=self._lock_aware,
        )
        g_trace_batch.add("NativeAPI.commit.Before", self.debug_id)
        try:
            reply: CommitReply = await self.db._commit.get_reply(
                req, timeout=self.db.knobs.COMMIT_TIMEOUT
            )
            g_trace_batch.add("NativeAPI.commit.After", self.debug_id)
        except TimedOut:
            # proxy unreachable: the commit may have happened
            raise CommitUnknownResult()
        except BrokenPromise:
            # the request was never delivered (proxy dead/stream closed
            # before delivery): the commit definitely did not start, so a
            # plain retry is safe — no fence needed
            raise NotCommitted()
        if reply.result == CommitResult.COMMITTED:
            self.committed_version = reply.version
            return reply.version
        if reply.result == CommitResult.TRANSACTION_TOO_OLD:
            raise TransactionTooOld()
        if reply.result == CommitResult.UNKNOWN:
            raise CommitUnknownResult()
        if reply.result == CommitResult.DATABASE_LOCKED:
            from ..roles.types import DatabaseLocked

            raise DatabaseLocked()  # not retryable: on_error re-raises
        raise NotCommitted()
