"""Client transaction API — the NativeAPI analog (fdbclient/NativeAPI.actor.cpp).

`Database` is the connection handle; `Transaction` implements the FDB
transaction model: snapshot reads at a GRV-acquired read version
(getReadVersion :2821), buffered writes, conflict ranges accumulated per
read/write, OCC commit via the proxy (tryCommit :2412), and the retry loop
(`Database.run`, the `fdb.transactional` analog: on_error backoff + full
retry on NotCommitted / TransactionTooOld).

Reads route to storage servers by key partition (the client's location
cache, getKeyLocation_internal :1085 — here a static map handed out by the
cluster; invalidation/refresh arrives with data distribution).
"""

from __future__ import annotations

from ..roles.proxy import KeyPartitionMap
from ..roles.types import (
    CommitReply,
    CommitResult,
    CommitTransactionRequest,
    CommitUnknownResult,
    FutureVersion,
    GetKeyValuesRequest,
    GetReadVersionRequest,
    GetValueRequest,
    Mutation,
    MutationType,
    NotCommitted,
    TransactionTooOld,
    Version,
)
from ..rpc.stream import RequestStreamRef
from ..runtime.core import DeterministicRandom, EventLoop, TimedOut
from ..keys import key_after


class ClusterView:
    """The client's window onto the current cluster generation — the
    MonitorLeader/cluster-file analog.  The control plane mutates these
    attributes on recovery; every Transaction reads them per call, so
    clients follow failovers without restarting."""

    def __init__(
        self,
        grv_ref: RequestStreamRef,
        commit_ref: RequestStreamRef,
        storage_map: KeyPartitionMap,  # members: {"getvalue": ref, "getkeyvalues": ref}
        epoch: int = 0,
    ) -> None:
        self.grv = grv_ref
        self.commit = commit_ref
        self.smap = storage_map
        self.epoch = epoch


class Database:
    def __init__(
        self,
        loop: EventLoop,
        view: ClusterView,
        rng: DeterministicRandom,
    ) -> None:
        self.loop = loop
        self.view = view
        self._rng = rng.split()

    @property
    def _grv(self) -> RequestStreamRef:
        return self.view.grv

    @property
    def _commit(self) -> RequestStreamRef:
        return self.view.commit

    @property
    def _smap(self) -> KeyPartitionMap:
        return self.view.smap

    def create_transaction(self) -> "Transaction":
        return Transaction(self)

    async def watch(self, key: bytes):
        """Future resolving when `key`'s value changes from its current
        value (fdbclient watch semantics: register against the storage
        server owning the key)."""
        from ..roles.types import WatchValueRequest

        tr = self.create_transaction()
        current = await tr.get(key, snapshot=True)
        v = await tr.get_read_version()
        refs = self._smap.member_for_key(key)
        return refs["watch"].get_reply(WatchValueRequest(key, current, v))

    async def run(self, fn, max_retries: int = 50):
        """Retry loop (fdb.transactional): run fn(tr), commit; on retryable
        errors back off and start over with a fresh read version.
        CommitUnknownResult is retried too — safe for idempotent or
        self-verifying transactions, the reference's contract."""
        backoff = 0.01
        for _attempt in range(max_retries):
            tr = self.create_transaction()
            try:
                result = await fn(tr)
                await tr.commit()
                return result
            except (
                NotCommitted,
                TransactionTooOld,
                FutureVersion,
                CommitUnknownResult,
                TimedOut,
            ):
                await self.loop.delay(backoff * (0.5 + self._rng.random()))
                backoff = min(backoff * 2, 1.0)
        raise NotCommitted(f"transaction failed after {max_retries} retries")


class Transaction:
    def __init__(self, db: Database) -> None:
        self.db = db
        self._read_version: Version | None = None
        self._mutations: list[Mutation] = []
        self._read_ranges: list[tuple[bytes, bytes]] = []
        self._write_ranges: list[tuple[bytes, bytes]] = []
        self.committed_version: Version | None = None

    # -- read version -------------------------------------------------------
    async def get_read_version(self) -> Version:
        if self._read_version is None:
            reply = await self.db._grv.get_reply(GetReadVersionRequest(), timeout=5.0)
            self._read_version = reply.version
        return self._read_version

    # -- reads --------------------------------------------------------------
    async def get(self, key: bytes, snapshot: bool = False) -> bytes | None:
        v = await self.get_read_version()
        refs = self.db._smap.member_for_key(key)
        reply = await refs["getvalue"].get_reply(GetValueRequest(key, v), timeout=5.0)
        if not snapshot:
            self._read_ranges.append((key, key_after(key)))
        return reply.value

    async def get_range(
        self, begin: bytes, end: bytes, limit: int = 10000, snapshot: bool = False
    ) -> list[tuple[bytes, bytes]]:
        v = await self.get_read_version()
        out: list[tuple[bytes, bytes]] = []
        smap = self.db._smap
        # walk shards left to right (the client iterates locations :1228)
        for idx in range(len(smap.members)):
            clip = smap.clip_to_member(idx, begin, end)
            if clip is None:
                continue
            b, e = clip
            reply = await smap.members[idx]["getkeyvalues"].get_reply(
                GetKeyValuesRequest(b, e, v, limit - len(out)), timeout=5.0
            )
            out.extend(reply.data)
            if len(out) >= limit:
                break
        if not snapshot:
            self._read_ranges.append((begin, end))
        return out[:limit]

    # -- writes -------------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        self._mutations.append(Mutation(MutationType.SET_VALUE, key, value))
        self._write_ranges.append((key, key_after(key)))

    def clear(self, key: bytes) -> None:
        self.clear_range(key, key_after(key))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self._mutations.append(Mutation(MutationType.CLEAR_RANGE, begin, end))
        self._write_ranges.append((begin, end))

    def atomic_op(self, op: MutationType, key: bytes, operand: bytes) -> None:
        self._mutations.append(Mutation(op, key, operand))
        self._write_ranges.append((key, key_after(key)))

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        self._read_ranges.append((begin, end))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        self._write_ranges.append((begin, end))

    # -- commit -------------------------------------------------------------
    async def commit(self) -> Version:
        if not self._mutations and not self._write_ranges:
            self.committed_version = self._read_version or 0
            return self.committed_version  # read-only: nothing to commit
        v = await self.get_read_version()
        req = CommitTransactionRequest(
            read_snapshot=v,
            read_conflict_ranges=list(self._read_ranges),
            write_conflict_ranges=list(self._write_ranges),
            mutations=list(self._mutations),
        )
        try:
            reply: CommitReply = await self.db._commit.get_reply(req, timeout=5.0)
        except TimedOut:
            # proxy unreachable: the commit may have happened
            raise CommitUnknownResult()
        if reply.result == CommitResult.COMMITTED:
            self.committed_version = reply.version
            return reply.version
        if reply.result == CommitResult.TRANSACTION_TOO_OLD:
            raise TransactionTooOld()
        if reply.result == CommitResult.UNKNOWN:
            raise CommitUnknownResult()
        raise NotCommitted()
