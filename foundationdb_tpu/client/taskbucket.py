"""TaskBucket — a distributed, transactional task queue IN the keyspace
(fdbclient/TaskBucket.actor.cpp: the work-scheduling layer the reference's
backup/DR agents are built on).

Everything is ordinary transactional data, so the queue inherits the
database's guarantees: adding a task is atomic with the transaction that
decides to add it, claiming is contention-checked (two workers cannot claim
the same task), and a claimed task whose worker dies is RE-queued when its
lease — measured in database versions, the cluster's only shared clock —
expires.  Execution is therefore at-least-once; handlers must be
idempotent (exactly the reference's contract).

Layout (tuple-layer keys under the bucket prefix):
    (prefix, "a", task_id)            -> packed params     (available)
    (prefix, "c", lease_end, task_id) -> packed params     (claimed)
"""

from __future__ import annotations

from .tuple_layer import Subspace
from ..runtime.core import ActorCancelled, TaskPriority


def _pack_params(params: dict[bytes, bytes]) -> bytes:
    from ..runtime.serialize import BinaryWriter

    w = BinaryWriter().u32(len(params))
    for k in sorted(params):
        w.bytes_(k).bytes_(params[k])
    return w.data()


def _unpack_params(blob: bytes) -> dict[bytes, bytes]:
    from ..runtime.serialize import BinaryReader

    r = BinaryReader(blob)
    return {r.bytes_(): r.bytes_() for _ in range(r.u32())}


class Task:
    def __init__(self, task_id: bytes, params: dict[bytes, bytes],
                 lease_end: int) -> None:
        self.id = task_id
        self.params = params
        self.lease_end = lease_end


class TaskBucket:
    def __init__(self, prefix: bytes = b"tb",
                 lease_versions: int = 2_000_000) -> None:
        self.space = Subspace((prefix,))
        self.avail = self.space.subspace(("a",))
        self.claimed = self.space.subspace(("c",))
        self.lease_versions = lease_versions  # ~2s of version time

    # -- producer ------------------------------------------------------------
    def add(self, tr, task_id: bytes, params: dict[bytes, bytes]) -> None:
        """Transactional add: atomic with whatever else `tr` does."""
        params = {**params, b"__type__": params.get(b"__type__", b"")}
        tr.set(self.avail.pack((task_id,)), _pack_params(params))

    # -- consumer ------------------------------------------------------------
    async def claim_one(self, tr) -> Task | None:
        """Claim the first available task: move it under (c, lease_end) —
        the write conflict on the moved key is what makes two concurrent
        claimers collide (one retries and takes the next task)."""
        rows = await tr.get_range(*self.avail.range(), limit=1)
        if not rows:
            await self._requeue_expired(tr, limit=5)
            return None
        key, blob = rows[0]
        (task_id,) = self.avail.unpack(key)
        v = await tr.get_read_version()
        lease_end = v + self.lease_versions
        tr.clear(key)
        tr.set(self.claimed.pack((lease_end, task_id)), blob)
        return Task(task_id, _unpack_params(blob), lease_end)

    def extend(self, tr, task: Task, new_lease_end: int) -> None:
        tr.clear(self.claimed.pack((task.lease_end, task.id)))
        tr.set(
            self.claimed.pack((new_lease_end, task.id)),
            _pack_params(task.params),
        )
        task.lease_end = new_lease_end

    def finish(self, tr, task: Task) -> None:
        """Done: remove the claim.  Run inside the handler's FINAL
        transaction so completion is atomic with the task's own writes."""
        tr.clear(self.claimed.pack((task.lease_end, task.id)))

    async def _requeue_expired(self, tr, limit: int = 5) -> int:
        """Leases are version-ordered keys: everything below (c, now) is an
        expired claim from a dead/stalled worker — move it back."""
        v = await tr.get_read_version()
        begin, _end = self.claimed.range()
        upto = self.claimed.pack((v,))
        rows = await tr.get_range(begin, upto, limit=limit)
        for key, blob in rows:
            _lease, task_id = self.claimed.unpack(key)
            tr.clear(key)
            tr.set(self.avail.pack((task_id,)), blob)
        return len(rows)

    async def is_empty(self, tr) -> bool:
        a = await tr.get_range(*self.avail.range(), limit=1)
        c = await tr.get_range(*self.claimed.range(), limit=1)
        return not a and not c


class TaskBucketExecutor:
    """Worker pool draining a bucket: claim → run handler → finish, with
    the at-least-once re-queue covering worker death (the reference's
    backup agents run exactly this loop)."""

    def __init__(self, db, bucket: TaskBucket, handlers: dict[bytes, callable],
                 poll_interval: float = 0.05) -> None:
        self.db = db
        self.bucket = bucket
        self.handlers = handlers
        self.poll_interval = poll_interval
        self.executed: list[bytes] = []
        self._stopped = False
        self._task = db.loop.spawn(self._run(), TaskPriority.DEFAULT_ENDPOINT,
                                   "taskbucket-worker")

    async def _run(self) -> None:
        while not self._stopped:
            claimed = None

            async def fn(tr):
                nonlocal claimed
                claimed = await self.bucket.claim_one(tr)

            try:
                await self.db.run(fn)
            except ActorCancelled:
                raise  # stop() cancelled the worker: die, don't keep polling
            except Exception:  # noqa: BLE001 — cluster transient: retry
                claimed = None
            if claimed is None:
                await self.db.loop.delay(self.poll_interval)
                continue
            handler = self.handlers.get(claimed.params.get(b"__type__", b""))
            if handler is not None:
                await handler(self.db, claimed)
            self.executed.append(claimed.id)

            async def done(tr):
                self.bucket.finish(tr, claimed)

            try:
                await self.db.run(done)
            except ActorCancelled:
                raise  # cancelled mid-finish: the lease re-queues the task
            except Exception:  # noqa: BLE001 — lease will re-queue it
                pass

    def stop(self) -> None:
        self._stopped = True
        self._task.cancel()
