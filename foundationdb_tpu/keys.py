"""Fixed-width key encoding for device kernels.

FoundationDB compares keys as arbitrary byte strings (reference:
fdbserver/SkipList.cpp:147-196 builds an elaborate per-byte ordering for its
sort; flow/Arena.h StringRef::compare is plain memcmp).  TPU kernels need
fixed-width lanes, so we encode a key of up to ``4*num_words`` bytes as
``num_words`` big-endian uint32 words (zero padded) followed by one length
word:

    enc(k) = (w_0, ..., w_{n-1}, len(k))

Lexicographic order over the ``n+1`` uint32 lanes equals byte-string order:
the first differing padded byte decides, and when one key is a zero-padded
prefix of the other (including trailing-NUL cases like ``b"a"`` vs
``b"a\\x00"``) the length word breaks the tie exactly as memcmp-then-length
does.

A sentinel of all-0xFFFFFFFF lanes sorts strictly after every real key
(real keys have length <= 4*num_words < 2**32) and is used to pad unused
slots in device arrays.
"""

from __future__ import annotations

import numpy as np

# Default: 32-byte keys -> 8 data words + 1 length word.  The reference's
# published benchmarks use 16-byte keys (documentation/sphinx/source/
# performance.rst:14); 32 gives headroom for tuple-encoded keys.
DEFAULT_MAX_KEY_BYTES = 32


def num_words(max_key_bytes: int = DEFAULT_MAX_KEY_BYTES) -> int:
    """Total lanes per encoded key (data words + 1 length word)."""
    if max_key_bytes <= 0 or max_key_bytes % 4:
        raise ValueError("max_key_bytes must be a positive multiple of 4")
    return max_key_bytes // 4 + 1


def sentinel(max_key_bytes: int = DEFAULT_MAX_KEY_BYTES) -> np.ndarray:
    """A key greater than any encodable key; pads unused device slots."""
    return np.full((num_words(max_key_bytes),), 0xFFFFFFFF, dtype=np.uint32)


def encode_keys(keys: list[bytes], max_key_bytes: int = DEFAULT_MAX_KEY_BYTES) -> np.ndarray:
    """Encode a list of byte keys -> uint32[len(keys), num_words].

    Raises KeyTooLongError for keys longer than max_key_bytes; callers that
    must handle arbitrary-length keys (FDB allows up to 10KB) catch this and
    route the batch to a host-side implementation (see conflict/tpu.py).
    """
    kw = num_words(max_key_bytes) - 1  # validates max_key_bytes
    n = len(keys)
    out = np.zeros((n, kw + 1), dtype=np.uint32)
    if n == 0:
        return out
    lens = np.fromiter((len(k) for k in keys), count=n, dtype=np.int64)
    if lens.max() > max_key_bytes:
        i = int(np.argmax(lens))
        raise KeyTooLongError(f"key of {len(keys[i])} bytes exceeds {max_key_bytes}")
    # Vectorized gather from the concatenated byte stream (hot path: the
    # resolver encodes every conflict-range endpoint of every batch).
    flat = np.frombuffer(b"".join(keys), dtype=np.uint8)
    starts = np.zeros(n, dtype=np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    cols = np.arange(max_key_bytes, dtype=np.int64)
    mask = cols[None, :] < lens[:, None]
    idx = np.minimum(starts[:, None] + cols[None, :], max(len(flat) - 1, 0))
    buf = np.where(mask, flat[idx] if len(flat) else np.uint8(0), np.uint8(0))
    out[:, kw] = lens
    # big-endian word packing: byte j contributes << (8 * (3 - j%4))
    words = (
        (buf[:, 0::4].astype(np.uint32) << 24)
        | (buf[:, 1::4].astype(np.uint32) << 16)
        | (buf[:, 2::4].astype(np.uint32) << 8)
        | (buf[:, 3::4].astype(np.uint32))
    )
    out[:, :kw] = words
    return out


def encode_fixed(
    key_bytes: np.ndarray, max_key_bytes: int = DEFAULT_MAX_KEY_BYTES
) -> np.ndarray:
    """Encode uint8[n, L] equal-length keys -> uint32[n, num_words] lanes.

    Vectorized matrix form of encode_keys for callers that already hold keys
    as a byte matrix (benchmarks, packed proxy batches).  Single source of
    truth for the lane layout lives here next to encode_keys.
    """
    kw = num_words(max_key_bytes) - 1
    n, L = key_bytes.shape
    if L > max_key_bytes:
        raise KeyTooLongError(f"{L}-byte keys exceed {max_key_bytes}")
    out = np.zeros((n, kw + 1), dtype=np.uint32)
    padded = np.zeros((n, 4 * kw), dtype=np.uint8)
    padded[:, :L] = key_bytes
    out[:, :kw] = (
        (padded[:, 0::4].astype(np.uint32) << 24)
        | (padded[:, 1::4].astype(np.uint32) << 16)
        | (padded[:, 2::4].astype(np.uint32) << 8)
        | padded[:, 3::4].astype(np.uint32)
    )
    out[:, kw] = L
    return out


def decode_key(enc: np.ndarray) -> bytes:
    """Inverse of encode_keys for a single encoded key."""
    kw = enc.shape[-1] - 1
    length = int(enc[kw])
    b = bytearray()
    for w in range(kw):
        v = int(enc[w])
        b += bytes(((v >> 24) & 0xFF, (v >> 16) & 0xFF, (v >> 8) & 0xFF, v & 0xFF))
    return bytes(b[:length])


class KeyTooLongError(ValueError):
    pass


def key_after(key: bytes) -> bytes:
    """First key strictly after ``key``: key + b'\\x00' (reference:
    fdbclient/FDBTypes.h keyAfter)."""
    return key + b"\x00"


def strinc(key: bytes) -> bytes:
    """First key not prefixed by ``key`` (reference: flow strinc): strip
    trailing 0xFF bytes then increment the last byte."""
    k = key.rstrip(b"\xff")
    if not k:
        raise ValueError("strinc of all-0xFF key has no upper bound")
    return k[:-1] + bytes([k[-1] + 1])
