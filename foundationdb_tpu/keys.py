"""Fixed-width key encoding for device kernels.

FoundationDB compares keys as arbitrary byte strings (reference:
fdbserver/SkipList.cpp:147-196 builds an elaborate per-byte ordering for its
sort; flow/Arena.h StringRef::compare is plain memcmp).  TPU kernels need
fixed-width lanes, so we encode a key of up to ``4*num_words`` bytes as
``num_words`` big-endian uint32 words (zero padded) followed by one length
word:

    enc(k) = (w_0, ..., w_{n-1}, len(k))

Lexicographic order over the ``n+1`` uint32 lanes equals byte-string order:
the first differing padded byte decides, and when one key is a zero-padded
prefix of the other (including trailing-NUL cases like ``b"a"`` vs
``b"a\\x00"``) the length word breaks the tie exactly as memcmp-then-length
does.

A sentinel of all-0xFFFFFFFF lanes sorts strictly after every real key
(real keys have length <= 4*num_words < 2**32) and is used to pad unused
slots in device arrays.
"""

from __future__ import annotations

import threading

import numpy as np

# Default: 32-byte keys -> 8 data words + 1 length word.  The reference's
# published benchmarks use 16-byte keys (documentation/sphinx/source/
# performance.rst:14); 32 gives headroom for tuple-encoded keys.
DEFAULT_MAX_KEY_BYTES = 32


def num_words(max_key_bytes: int = DEFAULT_MAX_KEY_BYTES) -> int:
    """Total lanes per encoded key (data words + 1 length word)."""
    if max_key_bytes <= 0 or max_key_bytes % 4:
        raise ValueError("max_key_bytes must be a positive multiple of 4")
    return max_key_bytes // 4 + 1


def sentinel(max_key_bytes: int = DEFAULT_MAX_KEY_BYTES) -> np.ndarray:
    """A key greater than any encodable key; pads unused device slots."""
    return np.full((num_words(max_key_bytes),), 0xFFFFFFFF, dtype=np.uint32)


def encode_keys(keys: list[bytes], max_key_bytes: int = DEFAULT_MAX_KEY_BYTES) -> np.ndarray:
    """Encode a list of byte keys -> uint32[len(keys), num_words].

    Raises KeyTooLongError for keys longer than max_key_bytes; callers that
    must handle arbitrary-length keys (FDB allows up to 10KB) catch this and
    route the batch to a host-side implementation (see conflict/tpu.py).
    """
    n = len(keys)
    if n == 0:
        return np.zeros((n, num_words(max_key_bytes)), dtype=np.uint32)
    lens = np.fromiter(map(len, keys), count=n, dtype=np.int64)
    return encode_concat(b"".join(keys), lens, max_key_bytes)


class _EncodeScratch(threading.local):
    """Grow-only staging buffers reused across encode_concat calls — the
    resolver packs a batch every few milliseconds, and reallocating the
    zero-padded stream copy plus the per-chunk gather temporaries was a
    measurable slice of encode_ms (the PackArena treatment, applied to the
    encoder's own scratch).  Thread-local so pipelined feeder threads never
    share a buffer."""

    def __init__(self) -> None:
        self.flatp = np.zeros(0, dtype=np.uint8)
        self.idx: np.ndarray | None = None
        self.buf: np.ndarray | None = None
        self.mask: np.ndarray | None = None

    def stream(self, flat: np.ndarray, L: int, pad: int) -> np.ndarray:
        need = L + pad
        if self.flatp.size < need:
            self.flatp = np.zeros(max(need, 2 * self.flatp.size), np.uint8)
        self.flatp[:L] = flat
        self.flatp[L:need] = 0  # pad region may hold a previous stream
        return self.flatp

    def chunk(self, rows: int, width: int, idt) -> tuple:
        if (
            self.idx is None
            or self.idx.dtype != idt
            or self.idx.shape[0] < rows
            or self.idx.shape[1] != width
        ):
            self.idx = np.empty((rows, width), dtype=idt)
            self.buf = np.empty((rows, width), dtype=np.uint8)
            self.mask = np.empty((rows, width), dtype=bool)
        return self.idx[:rows], self.buf[:rows], self.mask[:rows]


_scratch = _EncodeScratch()


def encode_concat(
    flat: bytes | bytearray | memoryview | np.ndarray,
    lens: np.ndarray,
    max_key_bytes: int = DEFAULT_MAX_KEY_BYTES,
) -> np.ndarray:
    """Batch encoder over an already-concatenated byte stream: key i occupies
    flat[sum(lens[:i]) : sum(lens[:i+1])].  One np.frombuffer view + one
    vectorized gather — no per-key Python call, which is what the resolver's
    bulk batch packer needs (it flattens every conflict-range endpoint of a
    batch into one stream and encodes them all at once).  encode_keys is the
    list-of-bytes convenience wrapper around this."""
    kw = num_words(max_key_bytes) - 1  # validates max_key_bytes
    lens = np.asarray(lens, dtype=np.int64)
    n = lens.shape[0]
    out = np.zeros((n, kw + 1), dtype=np.uint32)
    if n == 0:
        return out
    if isinstance(flat, np.ndarray):
        flat = np.ascontiguousarray(flat, dtype=np.uint8)
    else:
        flat = np.frombuffer(flat, dtype=np.uint8)
    if lens.max() > max_key_bytes:
        i = int(np.argmax(lens))
        raise KeyTooLongError(f"key of {int(lens[i])} bytes exceeds {max_key_bytes}")
    # Vectorized gather from the concatenated byte stream (hot path: the
    # resolver encodes every conflict-range endpoint of every batch).
    # Cache-conscious: int32 index math (len(flat) < 2**31 — a batch's key
    # stream is megabytes), an in-bounds gather off a zero-padded stream
    # with an in-place mask multiply instead of np.where temporaries, and
    # the big-endian word packing done by a single dtype view + byteswap
    # astype rather than four strided slice copies.
    L = len(flat)
    flatp = _scratch.stream(flat, L, max_key_bytes)
    # gather indices reach L + max_key_bytes - 1 (the zero pad), so the
    # int32 fast path needs headroom for the pad region too
    idt = np.int32 if L + max_key_bytes < 2**31 else np.int64
    starts = np.zeros(n, dtype=idt)
    np.cumsum(lens[:-1], out=starts[1:], dtype=idt)
    cols = np.arange(max_key_bytes, dtype=idt)
    lens_t = lens.astype(idt)
    out[:, kw] = lens
    # chunked so the per-chunk index/byte temporaries stay cache-resident
    # (one 50K-key gather measured ~2x slower than the same work in 8K
    # slices); in bounds by construction: starts[i] <= L, so starts[i] +
    # col < L + max_key_bytes — reads past a key's end land in the next
    # key's bytes or the zero pad, and the mask multiply zeroes them.
    step = 8192
    idx, buf, mask = _scratch.chunk(min(step, n), max_key_bytes, idt)
    for i in range(0, n, step):
        j = min(i + step, n)
        c = j - i
        np.add(starts[i:j, None], cols[None, :], out=idx[:c])
        np.take(flatp, idx[:c], out=buf[:c])
        np.less(cols[None, :], lens_t[i:j, None], out=mask[:c])
        np.multiply(buf[:c], mask[:c], out=buf[:c], casting="unsafe")
        # big-endian word view assigns straight into out (numpy byteswaps
        # on the cast copy — no astype temporary)
        out[i:j, :kw] = buf[:c].view(">u4")
    return out


def encode_fixed(
    key_bytes: np.ndarray, max_key_bytes: int = DEFAULT_MAX_KEY_BYTES
) -> np.ndarray:
    """Encode uint8[n, L] equal-length keys -> uint32[n, num_words] lanes.

    Vectorized matrix form of encode_keys for callers that already hold keys
    as a byte matrix (benchmarks, packed proxy batches).  Single source of
    truth for the lane layout lives here next to encode_keys.
    """
    kw = num_words(max_key_bytes) - 1
    n, L = key_bytes.shape
    if L > max_key_bytes:
        raise KeyTooLongError(f"{L}-byte keys exceed {max_key_bytes}")
    out = np.zeros((n, kw + 1), dtype=np.uint32)
    padded = np.zeros((n, 4 * kw), dtype=np.uint8)
    padded[:, :L] = key_bytes
    out[:, :kw] = (
        (padded[:, 0::4].astype(np.uint32) << 24)
        | (padded[:, 1::4].astype(np.uint32) << 16)
        | (padded[:, 2::4].astype(np.uint32) << 8)
        | padded[:, 3::4].astype(np.uint32)
    )
    out[:, kw] = L
    return out


def decode_key(enc: np.ndarray) -> bytes:
    """Inverse of encode_keys for a single encoded key."""
    kw = enc.shape[-1] - 1
    length = int(enc[kw])
    b = bytearray()
    for w in range(kw):
        v = int(enc[w])
        b += bytes(((v >> 24) & 0xFF, (v >> 16) & 0xFF, (v >> 8) & 0xFF, v & 0xFF))
    return bytes(b[:length])


class KeyTooLongError(ValueError):
    pass


def key_after(key: bytes) -> bytes:
    """First key strictly after ``key``: key + b'\\x00' (reference:
    fdbclient/FDBTypes.h keyAfter)."""
    return key + b"\x00"


def strinc(key: bytes) -> bytes:
    """First key not prefixed by ``key`` (reference: flow strinc): strip
    trailing 0xFF bytes then increment the last byte."""
    k = key.rstrip(b"\xff")
    if not k:
        raise ValueError("strinc of all-0xFF key has no upper bound")
    return k[:-1] + bytes([k[-1] + 1])
