"""ConflictSet plugin API — the seam where the TPU backend slots in.

Semantics mirror the reference's ConflictSet interface
(fdbserver/ConflictSet.h:27-60) and its use by the Resolver
(fdbserver/Resolver.actor.cpp:140-157):

  * A *batch* of transactions arrives with one commit version for the whole
    batch (assigned by the sequencer, fdbserver/masterserver.actor.cpp:831).
  * Each transaction carries a read snapshot version, read conflict ranges,
    and write conflict ranges (fdbclient/CommitTransaction.h:89).
  * Verdicts (reference ConflictBatch::TransactionCommitted enum):
      - TOO_OLD      if read_snapshot < oldest_version (the MVCC window floor;
                     detected at add time, SkipList.cpp:985)
      - CONFLICT     if any read range intersects a write range committed at a
                     version v with read_snapshot < v  (history conflict,
                     SkipList.cpp:1210), or intersects a write range of an
                     *earlier committed* transaction in the same batch
                     (intra-batch, SkipList.cpp:1133-1152 — order matters:
                     later transactions see earlier committed writes only)
      - COMMITTED    otherwise; its write ranges are then inserted at the
                     batch's commit version (SkipList.cpp:1260).
  * remove_before(v) garbage-collects write ranges with version < v and
    raises the TOO_OLD floor (SkipList.cpp:665).

Ranges are half-open [begin, end) over byte-string keys.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Sequence


class Verdict(enum.IntEnum):
    # Values match the reference's ConflictBatch::TransactionCommitResult
    # (fdbserver/ConflictSet.h:36-40): Conflict=0, TooOld=1, Committed=2.
    # The ordering is load-bearing: the proxy min-combines verdicts across
    # resolvers, so CONFLICT < TOO_OLD < COMMITTED means "any resolver that
    # couldn't verify (conflict or too-old) vetoes the commit".
    CONFLICT = 0
    TOO_OLD = 1
    COMMITTED = 2


@dataclasses.dataclass(frozen=True)
class TxInfo:
    """One transaction's conflict-relevant payload
    (fdbclient/CommitTransaction.h:89 CommitTransactionRef)."""

    read_snapshot: int
    read_ranges: Sequence[tuple[bytes, bytes]]
    write_ranges: Sequence[tuple[bytes, bytes]]


@dataclasses.dataclass
class KernelStats:
    """Uniform conflict-backend cost counters (the device kernel's
    profiling record, exposed by EVERY backend so parity checks can also
    compare cost, and so the status roll-up reads one shape regardless of
    which backend a resolver hosts).

    Wall times are host-measured (time.perf_counter): they are observability
    only and never feed back into simulation behavior, so determinism is
    unaffected.  `pack_s` is TxInfo→tensor/ABI marshalling, `resolve_s` the
    backend check itself, `merge_s` state maintenance outside the check
    (device GC/compaction kernels; CPU removeBefore).  `pack_s` further
    splits into `encode_s` (key flatten + lane encode), `pad_s` (bucketing
    and staging-arena fill) and `h2d_s` (explicit host→device staging, only
    where a caller stages with device_put — the input-pipeline counters of
    docs/KERNEL.md "Input pipeline").

    The per-phase splits (`sort_s`/`scan_s`/`append_s`/`compact_s`) mirror
    the device kernel's sort-scan decomposition (docs/KERNEL.md): sort =
    rank/sort-merge of the batch against the state, scan = the fused
    history + run-probe + intra-batch check, append = the incremental run
    append (the merge phase that replaced the per-batch full re-sort),
    compact = deferred run→main folds.  They are populated when the backend
    runs with phase timing on (FDBTPU_PHASE_TIMING=1, or profile_kernel.py
    --phase / bench.py's post pass) and stay zero otherwise — splitting a
    fused kernel requires per-phase dispatch barriers that the hot path must
    not pay."""

    backend: str = "?"
    batches: int = 0
    txns: int = 0
    aborted: int = 0            # CONFLICT verdicts
    pack_s: float = 0.0
    encode_s: float = 0.0       # pack phase: key flatten + lane encode
    pad_s: float = 0.0          # pack phase: bucket/pad/arena fill
    h2d_s: float = 0.0          # pack phase: explicit host->device staging
    resolve_s: float = 0.0
    merge_s: float = 0.0
    sort_s: float = 0.0         # phase: state rank / sort-merge
    scan_s: float = 0.0         # phase: history + run probe + intra-batch
    append_s: float = 0.0       # phase: incremental run append
    compact_s: float = 0.0      # phase: deferred run/recent→main folds
    real_rows: int = 0          # live read+write rows fed to the check
    padded_rows: int = 0        # rows after power-of-two bucketing
    recompiles: int = 0         # distinct static-shape combos jitted
    search_fallbacks: int = 0   # bucketed search replayed at full depth
    compactions: int = 0        # LSM recent→main + deferred run folds
    gc_calls: int = 0
    rows_reclaimed: int = 0     # boundaries freed by GC/compaction
    runs_appended: int = 0      # incremental merge: batches appended as runs
    full_merges: int = 0        # legacy path: full per-batch state rewrites
    merge_impl: str = "?"       # fold implementation (sort|scatter|gather)
    # wall seconds spent in run/recent→main folds keyed by the merge impl
    # that executed them — lets the status plane show which impl is live
    # AND what each impl actually cost when an autotune sweep mixes them.
    fold_wall_s: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        # per-batch resolve-time reservoir for p50/p99 (deterministic
        # xorshift inside ContinuousSample — no global random use)
        from ..runtime.metrics import ContinuousSample

        self.resolve_sample = ContinuousSample(256)

    def note_batch(self, n_txn: int, n_aborted: int, resolve_dt: float) -> None:
        self.batches += 1
        self.txns += n_txn
        self.aborted += n_aborted
        self.resolve_s += resolve_dt
        self.resolve_sample.add(resolve_dt)

    def snapshot(self, node_count: int = 0) -> dict:
        return {
            "backend": self.backend,
            "batches": self.batches,
            "txns": self.txns,
            "aborted": self.aborted,
            "abort_rate": self.aborted / self.txns if self.txns else 0.0,
            "occupancy": (
                self.real_rows / self.padded_rows if self.padded_rows else 1.0
            ),
            "rows_real": self.real_rows,
            "rows_padded": self.padded_rows,
            "recompiles": self.recompiles,
            "search_fallbacks": self.search_fallbacks,
            "compactions": self.compactions,
            "gc_calls": self.gc_calls,
            "rows_reclaimed": self.rows_reclaimed,
            "node_count": node_count,
            "runs_appended": self.runs_appended,
            "full_merges": self.full_merges,
            "merge_impl": self.merge_impl,
            "fold_ms": {k: v * 1e3 for k, v in sorted(self.fold_wall_s.items())},
            "pack_ms": self.pack_s * 1e3,
            "encode_ms": self.encode_s * 1e3,
            "pad_ms": self.pad_s * 1e3,
            "h2d_ms": self.h2d_s * 1e3,
            "resolve_ms": self.resolve_s * 1e3,
            "merge_ms": self.merge_s * 1e3,
            "phase": {
                "sort_ms": self.sort_s * 1e3,
                "scan_ms": self.scan_s * 1e3,
                "merge_ms": self.append_s * 1e3,
                "compact_ms": self.compact_s * 1e3,
            },
            "resolve_ms_p50": self.resolve_sample.percentile(0.5) * 1e3,
            "resolve_ms_p99": self.resolve_sample.percentile(0.99) * 1e3,
        }


class ResolveHandle:
    """Handle for a (possibly still in-flight) batch resolve.  `wait()`
    returns the per-txn verdicts, blocking until they are trustworthy —
    for device backends that means fetching the device verdict array AND
    draining the deferred validity checks (conflict/pipeline.py)."""

    def wait(self) -> list[Verdict]:
        raise NotImplementedError


class CompletedResolve(ResolveHandle):
    """Already-resolved handle: the synchronous backends' resolve_deferred
    result, and the pipelined backends' fallback when a batch cannot be
    deferred (empty batch, capacity fall-through)."""

    __slots__ = ("_verdicts",)

    def __init__(self, verdicts: list[Verdict]) -> None:
        self._verdicts = verdicts

    def wait(self) -> list[Verdict]:
        return self._verdicts


class ConflictSet:
    """Abstract conflict set; implementations: oracle (conflict/oracle.py),
    native C++ (conflict/native.py), TPU (conflict/tpu.py)."""

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        """Check all txns against history + each other; insert committed
        txns' writes at commit_version; return per-txn verdicts."""
        raise NotImplementedError

    def resolve_deferred(self, commit_version: int, txns: Sequence[TxInfo]) -> ResolveHandle:
        """Split-phase resolve: dispatch the batch and return a handle whose
        `wait()` yields the verdicts.  The state transition happens in
        dispatch order regardless of when handles are waited, so a caller
        may dispatch batch N+1 before draining batch N's verdicts (the
        resolver role's input pipeline, FDBTPU_PIPELINE).  Backends without
        a device stream resolve synchronously here — the default makes the
        split-phase caller exactly equivalent to the sequential one."""
        return CompletedResolve(self.resolve_batch(commit_version, txns))

    def remove_before(self, version: int) -> None:
        """GC write ranges older than `version`; txns with read_snapshot <
        version become TOO_OLD."""
        raise NotImplementedError

    @property
    def oldest_version(self) -> int:
        raise NotImplementedError

    @property
    def node_count(self) -> int:
        """Live boundary/node count of the committed-write state (the
        reference's skip-list node count); 0 where a backend can't say."""
        return 0

    def healthcheck(self) -> bool:
        """Cheap liveness probe of the backend: device-backed sets force a
        tiny host<->device round trip and raise on a sick device; pure-host
        backends are trivially healthy.  Used by the DeviceSupervisor
        (conflict/supervisor.py) before trusting a freshly built backend."""
        return True

    def kernel_stats(self) -> dict:
        """One-shape profiling snapshot (see KernelStats); backends that
        never instrumented themselves report zeros rather than failing."""
        stats = getattr(self, "stats", None)
        if stats is None:
            stats = self.stats = KernelStats(backend=type(self).__name__)
        try:
            nc = int(self.node_count)
        except Exception:  # noqa: BLE001 — a closed plugin handle etc.
            nc = 0
        return stats.snapshot(node_count=nc)

    def close(self) -> None:  # destroyConflictSet analog
        pass


class VerdictValidationError(ValueError):
    """A backend returned a malformed verdict list (wrong length or codes
    outside the Verdict enum).  A dedicated type so supervisors can
    distinguish corrupted device output from caller-side ValueErrors
    without string matching."""


def validate_verdicts(verdicts: Sequence, n_txn: int) -> None:
    """Sanity-check a backend's verdict list before trusting it: exactly one
    verdict per transaction and every code inside the enum — the cheap
    shield that turns a corrupted device readback (garbage D2H bytes) into
    a classified failure instead of a silently-wrong abort set."""
    if len(verdicts) != n_txn:
        raise VerdictValidationError(
            f"backend returned {len(verdicts)} verdicts for {n_txn} txns"
        )
    for v in verdicts:
        c = int(v)
        if c < int(Verdict.CONFLICT) or c > int(Verdict.COMMITTED):
            raise VerdictValidationError(
                f"verdict code {c} outside the Verdict enum"
            )


def validate_batch(commit_version: int, txns: Sequence[TxInfo], oldest: int) -> None:
    if commit_version < oldest:
        raise ValueError(f"commit_version {commit_version} < oldest_version {oldest}")
    for t in txns:
        if t.read_snapshot >= commit_version:
            raise ValueError("read_snapshot must precede commit_version")
        for b, e in list(t.read_ranges) + list(t.write_ranges):
            if not (isinstance(b, bytes) and isinstance(e, bytes)):
                raise TypeError("range endpoints must be bytes")
