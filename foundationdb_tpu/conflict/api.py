"""ConflictSet plugin API — the seam where the TPU backend slots in.

Semantics mirror the reference's ConflictSet interface
(fdbserver/ConflictSet.h:27-60) and its use by the Resolver
(fdbserver/Resolver.actor.cpp:140-157):

  * A *batch* of transactions arrives with one commit version for the whole
    batch (assigned by the sequencer, fdbserver/masterserver.actor.cpp:831).
  * Each transaction carries a read snapshot version, read conflict ranges,
    and write conflict ranges (fdbclient/CommitTransaction.h:89).
  * Verdicts (reference ConflictBatch::TransactionCommitted enum):
      - TOO_OLD      if read_snapshot < oldest_version (the MVCC window floor;
                     detected at add time, SkipList.cpp:985)
      - CONFLICT     if any read range intersects a write range committed at a
                     version v with read_snapshot < v  (history conflict,
                     SkipList.cpp:1210), or intersects a write range of an
                     *earlier committed* transaction in the same batch
                     (intra-batch, SkipList.cpp:1133-1152 — order matters:
                     later transactions see earlier committed writes only)
      - COMMITTED    otherwise; its write ranges are then inserted at the
                     batch's commit version (SkipList.cpp:1260).
  * remove_before(v) garbage-collects write ranges with version < v and
    raises the TOO_OLD floor (SkipList.cpp:665).

Ranges are half-open [begin, end) over byte-string keys.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Sequence


class Verdict(enum.IntEnum):
    # Values match the reference's ConflictBatch::TransactionCommitResult
    # (fdbserver/ConflictSet.h:36-40): Conflict=0, TooOld=1, Committed=2.
    # The ordering is load-bearing: the proxy min-combines verdicts across
    # resolvers, so CONFLICT < TOO_OLD < COMMITTED means "any resolver that
    # couldn't verify (conflict or too-old) vetoes the commit".
    CONFLICT = 0
    TOO_OLD = 1
    COMMITTED = 2


@dataclasses.dataclass(frozen=True)
class TxInfo:
    """One transaction's conflict-relevant payload
    (fdbclient/CommitTransaction.h:89 CommitTransactionRef)."""

    read_snapshot: int
    read_ranges: Sequence[tuple[bytes, bytes]]
    write_ranges: Sequence[tuple[bytes, bytes]]


class ConflictSet:
    """Abstract conflict set; implementations: oracle (conflict/oracle.py),
    native C++ (conflict/native.py), TPU (conflict/tpu.py)."""

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        """Check all txns against history + each other; insert committed
        txns' writes at commit_version; return per-txn verdicts."""
        raise NotImplementedError

    def remove_before(self, version: int) -> None:
        """GC write ranges older than `version`; txns with read_snapshot <
        version become TOO_OLD."""
        raise NotImplementedError

    @property
    def oldest_version(self) -> int:
        raise NotImplementedError

    def close(self) -> None:  # destroyConflictSet analog
        pass


def validate_batch(commit_version: int, txns: Sequence[TxInfo], oldest: int) -> None:
    if commit_version < oldest:
        raise ValueError(f"commit_version {commit_version} < oldest_version {oldest}")
    for t in txns:
        if t.read_snapshot >= commit_version:
            raise ValueError("read_snapshot must precede commit_version")
        for b, e in list(t.read_ranges) + list(t.write_ranges):
            if not (isinstance(b, bytes) and isinstance(e, bytes)):
                raise TypeError("range endpoints must be bytes")
