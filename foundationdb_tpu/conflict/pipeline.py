"""Host→device input pipeline for the conflict kernel.

The reference resolver hides its host-side costs with 16-way
software-pipelined skip-list cursors (fdbserver/SkipList.cpp:524,773); the
TPU-native analog is to overlap the HOST phase of batch N+1 — TxInfo
flattening, lane encoding, bucketing/padding, host→device staging — with the
DEVICE execution of batch N.  Three pieces (docs/KERNEL.md "Input
pipeline"):

  PackArena          preallocated per-bucket-shape staging buffers, rotated
                     double-buffered so pack_batch stops allocating (and
                     sentinel-filling) fresh padded arrays every batch.
  PipelinedPacker    a background thread that packs (and optionally stages
                     onto the device) batch N+1 while the caller's thread
                     drives batch N — the feeder for bench.py's
                     resolver-e2e stream.
  PipelinedConflictMixin
                     resolve_deferred() for the device-backed conflict
                     sets: dispatch sync=False, hand back a ResolveHandle,
                     and self-heal a deferred-validity failure by restoring
                     a pre-stream snapshot (jax arrays are immutable, so a
                     snapshot is a tuple of references) and replaying the
                     recorded batch/GC sequence through the sync path.

Determinism: none of this runs under deterministic simulation unless a
caller opts in (FDBTPU_PIPELINE, off by default — SimNetwork clusters keep
the synchronous resolve path), and even opted-in the verdict stream is
bit-identical to the synchronous path: packing is pure, dispatch order is
version order, and recovery replays the exact recorded inputs.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Sequence

import numpy as np

from .api import CompletedResolve, ResolveHandle, TxInfo, Verdict, validate_batch
from ..runtime.coverage import testcov


def pipeline_enabled(default: bool = False) -> bool:
    """FDBTPU_PIPELINE knob: opt-in for the split-phase resolver pipeline.
    Off by default (deterministic simulation and tier-1 CPU runs keep the
    synchronous path); malformed values fail loudly at construction (the
    knob-parsing convention)."""
    v = os.environ.get("FDBTPU_PIPELINE")
    if v is None:
        return default
    if v not in ("0", "1"):
        raise ValueError(f"FDBTPU_PIPELINE must be 0 or 1, got {v!r}")
    return v == "1"


class _RowSlot:
    __slots__ = ("b", "e", "t", "live")

    def __init__(self, n: int, W: int, sent_word: int) -> None:
        self.b = np.full((n, W), sent_word, dtype=np.uint32)
        self.e = np.full((n, W), sent_word, dtype=np.uint32)
        self.t = np.full(n, -1, dtype=np.int32)
        self.live = 0


class _TxnSlot:
    __slots__ = ("snap", "active", "live")

    def __init__(self, n: int) -> None:
        self.snap = np.zeros(n, dtype=np.int32)
        self.active = np.zeros(n, dtype=bool)
        self.live = 0


class PackArena:
    """Preallocated per-bucket-shape staging buffers for pack_batch.

    Every distinct (bucketed rows, key width) shape owns `depth` rotating
    slot copies: slot i serves batch N, slot (i+1) % depth serves batch N+1,
    so a batch whose arrays may still be read by an in-flight dispatch is
    never overwritten by the next pack.  Callers must bound their in-flight
    window to depth-1 batches (PipelinedConflictMixin enforces this;
    PipelinedPacker stages device copies before reusing a slot).  Only the
    previously-live pad region is re-sentinelled on reuse — the arena's
    whole point is that steady-state packing writes O(live rows), not
    O(bucket capacity)."""

    def __init__(self, depth: int = 2) -> None:
        if depth < 2:
            raise ValueError("PackArena depth must be >= 2 (double buffering)")
        self.depth = depth
        self._rows: dict[tuple[str, int, int], list[_RowSlot]] = {}
        self._txns: dict[int, list[_TxnSlot]] = {}
        self._turn: dict[tuple, int] = {}

    def _pick(self, pool: dict, key, make):
        slots = pool.get(key)
        if slots is None:
            slots = pool[key] = [make() for _ in range(self.depth)]
        i = self._turn.get(key, 0)
        self._turn[key] = (i + 1) % self.depth
        return slots[i]

    def rows(self, kind: str, n: int, W: int, sent_word: int) -> _RowSlot:
        """A (begin, end, txn-id) row slot for `n` bucketed rows; rows past
        the previous occupant's live count are already sentinel/-1.

        `kind` keeps the read and write pools distinct: each pool must
        rotate exactly ONCE per batch, or a same-shaped read and write
        class would share slots and reuse one while the previous batch's
        kernel (JAX zero-copies aligned numpy inputs on CPU) still reads
        it — a measured, alignment-dependent corruption."""
        s = self._pick(
            self._rows, (kind, n, W), lambda: _RowSlot(n, W, sent_word)
        )
        return s

    def txns(self, n: int) -> _TxnSlot:
        return self._pick(self._txns, n, lambda: _TxnSlot(n))


class DeferredResolve(ResolveHandle):
    """In-flight pipelined resolve: the device verdict array plus the
    stream-folded validity flag as of this batch's dispatch.  `wait()`
    drains through the owning conflict set so failures recover in order.

    The handle keeps the original TxInfo list, NOT the packed arrays: the
    staging-arena buffers rotate and may be rewritten by later packs, but
    packing is pure, so a recovery replay re-packs from the TxInfo stream
    and reproduces the dispatch-time tensors exactly."""

    __slots__ = (
        "owner", "version", "n_txn", "txns", "verdict_dev", "ok_dev",
        "gc_after", "_result",
    )

    def __init__(self, owner, version: int, txns, verdict_dev, ok_dev) -> None:
        self.owner = owner
        self.version = version
        self.n_txn = len(txns)
        self.txns = txns
        self.verdict_dev = verdict_dev
        self.ok_dev = ok_dev
        self.gc_after: list[int] = []   # remove_before calls after dispatch
        self._result: list[Verdict] | None = None

    def wait(self) -> list[Verdict]:
        if self._result is None:
            self.owner._drain_deferred(self)
        assert self._result is not None
        return self._result


# after this many drained-but-replayable batches, validate the whole stream
# once (one folded-flag fetch) and advance the recovery snapshot — bounds
# both the replay window and the handles kept alive by a hot stream
_REPLAY_WINDOW = 8


class PipelinedConflictMixin:
    """resolve_deferred() for the device-backed conflict sets.

    Host classes provide: `_oldest`, `_offset`, `_offset_array`,
    `_max_key_bytes`, `_dev_ok`, `stats`, `resolve_arrays(...)`,
    `resolve_batch(...)`, `remove_before(...)`, `check_pipelined()`, and a
    class-level `_PIPELINE_SNAPSHOT_ATTRS` naming every piece of state a
    dispatch or GC can move.  jax arrays are immutable, so a snapshot is a
    dict of references; host-side ints/np arrays are rebound (never mutated
    in place) by the resolve paths, so references are safe there too.
    """

    _PIPELINE_SNAPSHOT_ATTRS: tuple[str, ...] = ()
    _pipeline_depth = 2

    def _pipeline_init(self) -> None:
        self._inflight: list[DeferredResolve] = []
        self._replayable: list[DeferredResolve] = []
        self._pipe_snapshot: dict | None = None
        # a slot is reused D packs later; with up to `depth` undrained
        # dispatches outstanding, D = depth + 1 keeps every in-flight
        # batch's arrays untouched until its kernel has completed
        self._arena = PackArena(depth=self._pipeline_depth + 1)

    def _take_snapshot(self) -> dict:
        return {
            a: getattr(self, a)
            for a in self._PIPELINE_SNAPSHOT_ATTRS
            if hasattr(self, a)
        }

    def resolve_deferred(self, commit_version: int, txns: Sequence[TxInfo]) -> ResolveHandle:
        from .device import pack_batch  # runtime import: device imports this module

        B = len(txns)
        if B == 0:
            return CompletedResolve(self.resolve_batch(commit_version, txns))
        validate_batch(commit_version, txns, self._oldest)
        # bound undrained dispatches so the arena never recycles a slot an
        # in-flight kernel may still read (see _pipeline_init)
        while len(self._inflight) >= self._pipeline_depth:
            self._drain_deferred(self._inflight[0])
        t0 = time.perf_counter()
        packed = pack_batch(
            txns, self._oldest, self._offset, self._max_key_bytes,
            arena=self._arena, stats=self.stats,
            offset_array=self._offset_array,
        )[:8]
        self.stats.pack_s += time.perf_counter() - t0
        if not self._inflight:
            self._pipe_snapshot = self._take_snapshot()
        try:
            verdict = self.resolve_arrays(commit_version, *packed, sync=False)
        except RuntimeError:
            # an internal near-capacity drain surfaced a deferred failure
            self._recover_inflight()
            return CompletedResolve(self.resolve_batch(commit_version, txns))
        if isinstance(verdict, np.ndarray):
            # the backend fell through to a synchronous resolve internally
            # (capacity margin): verdicts are already trustworthy
            if not self._inflight:
                self._pipe_snapshot = None
                self._replayable.clear()
            return CompletedResolve(
                [Verdict(int(c)) for c in verdict[:B]]
            )
        h = DeferredResolve(self, commit_version, list(txns), verdict, self._dev_ok)
        self._inflight.append(h)
        return h

    def _drain_deferred(self, upto: DeferredResolve) -> None:
        """Drain in dispatch order through `upto`; on a deferred-validity
        failure, recover the whole window (snapshot restore + sync replay)."""
        if upto._result is not None:
            return
        while self._inflight:
            h = self._inflight[0]
            v = np.asarray(h.verdict_dev)
            if not bool(np.asarray(h.ok_dev)):
                self._recover_inflight()
                return
            self._inflight.pop(0)
            h._result = [Verdict(int(c)) for c in v[: h.n_txn]]
            if self._inflight:
                # later dispatches already ride on h's state: keep h
                # replayable until the stream validates past it
                self._replayable.append(h)
                if len(self._replayable) >= _REPLAY_WINDOW and bool(
                    np.asarray(self._dev_ok)
                ):
                    # the fetched fold just validated EVERY dispatched batch
                    # (the fetch is a stream sync): drain the remainder of
                    # the window in place and reset the recovery state —
                    # a mid-window snapshot would be unusable, because the
                    # still-inflight dispatches are already baked into it
                    for hh in self._inflight:
                        hh._result = [
                            Verdict(int(c))
                            for c in np.asarray(hh.verdict_dev)[: hh.n_txn]
                        ]
                    self._inflight.clear()
            if not self._inflight:
                self._replayable.clear()
                self._pipe_snapshot = None
                self.check_pipelined()  # refresh host counts; known-valid
            if h is upto:
                return

    def _drain_all(self) -> None:
        if self._inflight:
            self._drain_deferred(self._inflight[-1])

    def _recover_inflight(self) -> None:
        """A deferred check failed somewhere in the in-flight window: restore
        the pre-window snapshot and replay every recorded batch (and the GC
        calls interleaved between them) through the sync path, which handles
        full-depth search fallback and capacity regrow exactly.  Replays go
        through resolve_batch from each handle's TxInfo stream — packing is
        pure, so this reproduces the dispatch-time tensors even though the
        arena buffers have rotated since.  Results for already-drained
        (replayable) batches were valid — the replay reproduces them
        bit-for-bit while rebuilding the state."""
        pending = self._inflight
        done = self._replayable
        snap = self._pipe_snapshot
        self._inflight, self._replayable, self._pipe_snapshot = [], [], None
        assert snap is not None, "deferred failure with no recovery snapshot"
        for a, val in snap.items():
            setattr(self, a, val)
        testcov("kernel.pipeline_recover")
        for h in done + pending:
            verdicts = self.resolve_batch(h.version, h.txns)
            if h._result is None:
                h._result = list(verdicts)
            for gv in h.gc_after:
                self.remove_before(gv)

    def abandon_inflight(self) -> None:
        """Drop every in-flight deferred handle WITHOUT touching the device.

        Called by the DeviceSupervisor when it discards a sick backend: the
        verdicts of the open window are recomputed by the supervisor's CPU
        replay, so fetching them here (a device round trip that may hang or
        raise on a lost device) must never happen — not even from close().
        After this, resolve/GC calls on this set are undefined; the owner
        is expected to drop the whole object."""
        self._inflight.clear()
        self._replayable.clear()
        self._pipe_snapshot = None

    def _note_pipeline_gc(self, version: int) -> None:
        """remove_before while batches are in flight: record the call on the
        newest dispatch so a recovery replays it at the right point."""
        if self._inflight:
            self._inflight[-1].gc_after.append(version)


class PipelinedPacker:
    """Background-thread double-buffered packer: packs (and optionally
    stages onto the device) batch N+1 while the caller drives batch N.

    `pack_fn(item)` runs on the worker thread and must return a tuple of
    numpy arrays; `stage(packed)` (optional — e.g. jax.device_put) runs on
    the worker thread too and its wall time lands in `stats.h2d_s`, giving
    the h2d leg of the encode/pad/h2d pack split.  Results come back in
    submission order.  `depth` bounds unconsumed packed batches, which is
    what makes arena reuse safe: pack_fn's arena needs depth+1 rotating
    slots at most, and the default PackArena depth of 2 matches the default
    pipeline depth of 1 outstanding batch.

    Never used under deterministic simulation (threads would break replay);
    the sim resolver's split-phase path defers on the DEVICE stream instead
    (PipelinedConflictMixin) and keeps packing on the caller's thread.
    """

    def __init__(
        self,
        pack_fn: Callable,
        *,
        depth: int = 2,
        stage: Callable | None = None,
        stats=None,
    ) -> None:
        self._pack_fn = pack_fn
        self._stage = stage
        self._stats = stats
        self._in: queue.Queue = queue.Queue()
        self._out: queue.Queue = queue.Queue()
        self._space = threading.Semaphore(depth)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._in.get()
            if item is _STOP:
                self._out.put((False, RuntimeError("PipelinedPacker closed")))
                return
            try:
                packed = self._pack_fn(item)
                if self._stage is not None:
                    t0 = time.perf_counter()
                    packed = self._stage(packed)
                    if self._stats is not None:
                        self._stats.h2d_s += time.perf_counter() - t0
                self._out.put((True, packed))
            except BaseException as e:  # noqa: BLE001 — re-raised at get()
                self._out.put((False, e))

    def submit(self, item) -> None:
        """Enqueue a batch for packing; blocks when `depth` packed batches
        are waiting unconsumed (backpressure = the arena-reuse bound)."""
        self._space.acquire()
        self._in.put(item)

    def get(self):
        """Next packed batch, in submission order; re-raises pack errors."""
        ok, payload = self._out.get()
        self._space.release()
        if not ok:
            raise payload
        return payload

    def close(self) -> None:
        self._in.put(_STOP)
        self._thread.join(timeout=10)


class _Stop:
    pass


_STOP = _Stop()
