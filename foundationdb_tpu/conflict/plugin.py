"""ConflictSet plugin loader — dlopen a backend behind the IConflictSet seam.

Models the reference's plugin pattern (fdbrpc/LoadPlugin.h:30-44: dlopen +
resolve a well-known symbol, used there to load TLS backends and named by the
north star as the seam for alternate conflict backends): a shared library
exporting the `fdbtpu_conflictset_*` C ABI (see native/conflictset.cpp)
becomes a ConflictSet implementation, keeping the resolver role and the
device path intact whichever backend is loaded.
"""

from __future__ import annotations

import ctypes
import time
from typing import Sequence

import numpy as np

from .api import ConflictSet, KernelStats, TxInfo, Verdict, validate_batch

_ABI = {
    "fdbtpu_conflictset_backend_name": (ctypes.c_char_p, []),
    "fdbtpu_conflictset_create": (ctypes.c_void_p, [ctypes.c_int64]),
    "fdbtpu_conflictset_destroy": (None, [ctypes.c_void_p]),
    "fdbtpu_conflictset_resolve": (
        ctypes.c_int,
        [
            ctypes.c_void_p,  # cs
            ctypes.c_int64,  # commit_version
            ctypes.c_int32,  # n_txn
            ctypes.POINTER(ctypes.c_int64),  # snapshots
            ctypes.POINTER(ctypes.c_int32),  # n_read_ranges
            ctypes.POINTER(ctypes.c_int32),  # n_write_ranges
            ctypes.POINTER(ctypes.c_uint8),  # key_bytes
            ctypes.POINTER(ctypes.c_int64),  # key_offsets
            ctypes.POINTER(ctypes.c_uint8),  # out_verdicts
        ],
    ),
    "fdbtpu_conflictset_remove_before": (None, [ctypes.c_void_p, ctypes.c_int64]),
    "fdbtpu_conflictset_oldest": (ctypes.c_int64, [ctypes.c_void_p]),
    "fdbtpu_conflictset_node_count": (ctypes.c_int64, [ctypes.c_void_p]),
}


class ConflictPlugin:
    """A loaded conflict-backend shared library; factory for PluginConflictSet."""

    def __init__(self, path: str) -> None:
        self._lib = ctypes.CDLL(path)  # raises OSError on missing/bad lib
        for name, (restype, argtypes) in _ABI.items():
            try:
                fn = getattr(self._lib, name)
            except AttributeError as e:  # symbol check, LoadPlugin.h:39-43
                raise OSError(f"plugin {path} lacks symbol {name}") from e
            fn.restype = restype
            fn.argtypes = argtypes
        self.path = path

    @property
    def backend_name(self) -> str:
        return self._lib.fdbtpu_conflictset_backend_name().decode()

    def create(self, oldest_version: int = 0) -> "PluginConflictSet":
        return PluginConflictSet(self._lib, oldest_version)


class PluginConflictSet(ConflictSet):
    """ConflictSet calling through the C ABI of a loaded plugin."""

    def __init__(self, lib, oldest_version: int) -> None:
        self._lib = lib
        self._handle = lib.fdbtpu_conflictset_create(oldest_version)
        self._oldest = oldest_version
        self.stats = KernelStats(backend="native")

    @property
    def oldest_version(self) -> int:
        return self._oldest

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        validate_batch(commit_version, txns, self._oldest)
        n = len(txns)
        t_pack = time.perf_counter()
        snapshots = np.fromiter(
            (t.read_snapshot for t in txns), dtype=np.int64, count=n
        )
        n_reads = np.fromiter(
            (len(t.read_ranges) for t in txns), dtype=np.int32, count=n
        )
        n_writes = np.fromiter(
            (len(t.write_ranges) for t in txns), dtype=np.int32, count=n
        )
        keys: list[bytes] = []
        for t in txns:
            for b, e in t.read_ranges:
                keys.append(b)
                keys.append(e)
            for b, e in t.write_ranges:
                keys.append(b)
                keys.append(e)
        key_bytes = np.frombuffer(b"".join(keys), dtype=np.uint8) if keys else np.zeros(0, np.uint8)
        offsets = np.zeros(len(keys) + 1, dtype=np.int64)
        np.cumsum([len(k) for k in keys], out=offsets[1:])
        self.stats.pack_s += time.perf_counter() - t_pack
        verdicts = self.resolve_packed(
            commit_version, snapshots, n_reads, n_writes, key_bytes, offsets
        )
        return [Verdict(int(v)) for v in verdicts]

    def resolve_packed(
        self,
        commit_version: int,
        snapshots: np.ndarray,  # int64[n]
        n_reads: np.ndarray,  # int32[n]
        n_writes: np.ndarray,  # int32[n]
        key_bytes: np.ndarray,  # uint8[total]
        offsets: np.ndarray,  # int64[n_keys+1]
    ) -> np.ndarray:
        """Packed fast path mirroring the C ABI directly (keys concatenated
        txn-by-txn: read (b,e)* then write (b,e)*).  Counterpart of
        DeviceConflictSet.resolve_arrays for marshal-free benchmarking and
        the packed proxy->resolver wire format."""
        if not self._handle:
            # a closed/destroyed plugin handle must fail loudly, not hand a
            # NULL pointer to the C ABI (a segfault the supervisor could
            # never classify)
            raise RuntimeError("conflict plugin handle closed")
        n = snapshots.shape[0]
        verdicts = np.zeros(max(n, 1), dtype=np.uint8)
        t0 = time.perf_counter()

        def p(arr, ty):
            return arr.ctypes.data_as(ctypes.POINTER(ty))

        rc = self._lib.fdbtpu_conflictset_resolve(
            self._handle,
            commit_version,
            n,
            p(np.ascontiguousarray(snapshots, np.int64), ctypes.c_int64),
            p(np.ascontiguousarray(n_reads, np.int32), ctypes.c_int32),
            p(np.ascontiguousarray(n_writes, np.int32), ctypes.c_int32),
            p(np.ascontiguousarray(key_bytes, np.uint8), ctypes.c_uint8),
            p(np.ascontiguousarray(offsets, np.int64), ctypes.c_int64),
            p(verdicts, ctypes.c_uint8),
        )
        if rc != 0:
            raise ValueError(
                f"commit_version {commit_version} not after the previous batch"
            )
        rows = (offsets.shape[0] - 1) // 2
        self.stats.real_rows += rows
        self.stats.padded_rows += rows  # the C ABI takes exact-size arrays
        self.stats.note_batch(
            n,
            int((verdicts[:n] == int(Verdict.CONFLICT)).sum()),
            time.perf_counter() - t0,
        )
        return verdicts[:n]

    def remove_before(self, version: int) -> None:
        if not self._handle:
            raise RuntimeError("conflict plugin handle closed")
        if version > self._oldest:
            self._oldest = version
            t0 = time.perf_counter()
            before = self.node_count
            self._lib.fdbtpu_conflictset_remove_before(self._handle, version)
            self.stats.gc_calls += 1
            self.stats.rows_reclaimed += max(0, before - self.node_count)
            self.stats.merge_s += time.perf_counter() - t0

    @property
    def node_count(self) -> int:
        if not self._handle:
            raise RuntimeError("conflict plugin handle closed")
        return int(self._lib.fdbtpu_conflictset_node_count(self._handle))

    def close(self) -> None:
        if self._handle:
            self._lib.fdbtpu_conflictset_destroy(self._handle)
            self._handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
