"""Pallas sort-scan conflict kernel — the committed-run probe.

The device backend's measured dominator was the committed-write MERGE: the
XLA lowering rewrote the full step function every batch (52.8 of ~57 ms/batch
at CAP=2^19, round-4 profiling).  The incremental redesign (conflict/device.py
"runs" layout) makes the merge an APPEND: each resolved batch's committed
write ranges become one sorted, disjoint interval *run* at a single commit
version, and runs fold into the main step function only at deferred
compactions.  What remains per batch is the check this kernel does — the
sort-scan conflict core:

  for each read range [rb, re) at snapshot `snap`, against each run k:
      conflict  iff  runs_ver[k] > snap          (MVCC version-window check)
                and  run k intersects [rb, re)   (segment-intersection scan)

Because a run's intervals are sorted and DISJOINT, their end keys are sorted
too, so the intersection test collapses to a rank + one neighbour row:

      rank = |{ i : begins[i] < re }|            (sort-merge of the query
                                                  against the run's key order)
      intersects  iff  rank > 0  and  ends[rank-1] > rb

The kernel fuses all three per (run, read-block) grid step: the run's begin
and end key tensors live in VMEM; the rank comes from a two-level scan — a
vectorized lexicographic count against a summary of every STRIDE-th begin
key (the merge-path coarse partition), then a counted compare inside the
one STRIDE-wide window the rank can occupy.  No state-sized scatters, no
HBM gathers: everything a block touches is VMEM-resident, which is exactly
the access pattern XLA's gather/scatter lowering denied us.

Lowering chain (the capability probe, `pallas_mode`):

  * "tpu"        — compiled Pallas on a real TPU backend (the production
                   lowering; shapes here are small enough that Mosaic's
                   (8, 128) tiling pads the W=5..9 lane dimension).
  * "interpret"  — `pl.pallas_call(..., interpret=True)`: the same kernel
                   body run by the Pallas interpreter on CPU.  Slow, but
                   bit-identical — tier-1 parity tests pin the kernel's
                   semantics to the oracle without TPU access.
  * None         — Pallas unavailable (or FDBTPU_PALLAS=off): callers fall
                   back to `run_conflicts_xla`, a vmapped full-depth binary
                   search with the identical contract, so no backend or test
                   ever depends on Pallas being importable.

All-integer and deterministic, like the rest of the conflict core: the probe
is a pure function of (reads, runs), so CPU interpret, XLA fallback and TPU
verdicts agree bit-for-bit.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

READ_BLOCK = 128    # reads per grid step (R is power-of-two bucketed, >= 16)
SUMMARY_STRIDE = 128  # begin keys per summary window (the coarse partition)


# ---------------------------------------------------------------------------
# capability probe


@functools.lru_cache(maxsize=1)
def pallas_available() -> bool:
    """Can `jax.experimental.pallas` be imported at all?  Cached: the probe
    runs in every DeviceConflictSet constructor."""
    try:
        from jax.experimental import pallas as _pl  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 — any import failure means "no pallas"
        return False


def pallas_mode(override: str | None = None) -> str | None:
    """Resolve the probe lowering: explicit override, else FDBTPU_PALLAS,
    else auto.  Returns "tpu" | "interpret" | None (None => XLA fallback).

    auto: compiled Pallas when the default backend is a TPU, XLA fallback
    otherwise — interpret mode is a *testing* lowering (orders of magnitude
    slower than XLA on CPU) and is never chosen implicitly.  Unknown values
    fail loudly, the knob-parsing convention."""
    v = override or os.environ.get("FDBTPU_PALLAS", "auto")
    if v in ("off", "0", "none"):
        return None
    if not pallas_available():
        if v in ("interpret", "tpu", "on", "1"):
            raise RuntimeError(
                f"FDBTPU_PALLAS={v!r} but jax.experimental.pallas is not importable"
            )
        return None
    if v == "interpret":
        return "interpret"
    if v in ("tpu", "on", "1"):
        return "tpu"
    if v == "auto":
        return "tpu" if jax.default_backend() == "tpu" else None
    raise ValueError(
        f"unknown FDBTPU_PALLAS value {v!r}; choose auto|tpu|interpret|off"
    )


# ---------------------------------------------------------------------------
# shared lexicographic compare (broadcasting twin of ops.search.lex_less,
# usable inside a Pallas kernel body)


def lex_less_b(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a < b over the trailing word axis, broadcasting over
    leading axes (ops.search.lex_less requires equal ranks; kernels compare
    [QB, 1, W] against [1, N, W])."""
    W = a.shape[-1]
    lt = a < b
    eq = a == b
    out = lt[..., W - 1]
    for w in range(W - 2, -1, -1):
        out = lt[..., w] | (eq[..., w] & out)
    return out


# ---------------------------------------------------------------------------
# the kernel


def _probe_kernel(ver_ref, rb_ref, re_ref, snap_ref, rok_ref, b_ref, e_ref,
                  out_ref, *, stride: int, run_cap: int):
    """One (read-block, run) grid step of the sort-scan probe.

    Grid is (R // READ_BLOCK, K) with the run axis MINOR, so each read
    block's output is produced by K consecutive steps and accumulated with
    the standard revisiting pattern (init at k == 0, OR afterwards)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    k = pl.program_id(1)
    begins = b_ref[0]            # [run_cap, W] — this run's interval begins
    ends = e_ref[0]              # [run_cap, W] — matching ends (also sorted)
    rb = rb_ref[...]             # [QB, W]
    re_ = re_ref[...]            # [QB, W]
    snap = snap_ref[...]         # [QB]
    rok = rok_ref[...]           # [QB] int32 0/1
    ver = ver_ref[k]             # this run's commit-version offset (SMEM)

    n_sum = run_cap // stride
    wins = begins.reshape(n_sum, stride, begins.shape[-1])
    summary = wins[:, 0, :]      # every stride-th begin key (merge-path posts)

    # coarse scan: how many summary posts sort before re?  rank lives in
    # window (coarse - 1); coarse == 0 means rank == 0 (begins[0] >= re).
    coarse = jnp.sum(
        lex_less_b(summary[None, :, :], re_[:, None, :]).astype(jnp.int32),
        axis=1,
    )                            # [QB]
    w_i = jnp.clip(coarse - 1, 0, n_sum - 1)
    window = jnp.take(wins, w_i, axis=0)        # [QB, stride, W]
    fine = jnp.sum(
        lex_less_b(window, re_[:, None, :]).astype(jnp.int32), axis=1
    )
    rank = jnp.where(coarse > 0, w_i * stride + fine, 0)

    # ends are sorted (disjoint intervals), so the candidate with the
    # largest end among begins < re is exactly ends[rank - 1]
    e_last = jnp.take(ends, jnp.clip(rank - 1, 0, run_cap - 1), axis=0)
    intersects = (rank > 0) & lex_less_b(rb, e_last)
    conf = ((rok > 0) & intersects & (ver > snap)).astype(jnp.int32)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = conf

    @pl.when(k > 0)
    def _accum():
        out_ref[...] = out_ref[...] | conf


@functools.lru_cache(maxsize=64)
def _build_probe(K: int, run_cap: int, W: int, R: int, interpret: bool):
    """Compile-cache the pallas_call for one (shape, mode) combo."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    qb = min(READ_BLOCK, R)
    stride = min(SUMMARY_STRIDE, run_cap)
    grid = (R // qb, K)
    kernel = functools.partial(_probe_kernel, stride=stride, run_cap=run_cap)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                    # runs_ver [K]
            pl.BlockSpec((qb, W), lambda q, k: (q, 0)),               # rb
            pl.BlockSpec((qb, W), lambda q, k: (q, 0)),               # re
            pl.BlockSpec((qb,), lambda q, k: (q,)),                   # snap
            pl.BlockSpec((qb,), lambda q, k: (q,)),                   # r_ok
            pl.BlockSpec((1, run_cap, W), lambda q, k: (k, 0, 0)),    # begins
            pl.BlockSpec((1, run_cap, W), lambda q, k: (k, 0, 0)),    # ends
        ],
        out_specs=pl.BlockSpec((qb,), lambda q, k: (q,)),
        out_shape=jax.ShapeDtypeStruct((R,), jnp.int32),
        interpret=interpret,
    )


def run_conflicts_pallas(rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver,
                         *, interpret: bool) -> jnp.ndarray:
    """Pallas lowering of the run probe.  Returns bool[R]: read i conflicts
    with some committed run newer than its snapshot."""
    K, run_cap, W = runs_b.shape
    R = rb.shape[0]
    fn = _build_probe(K, run_cap, W, R, interpret)
    out = fn(
        runs_ver, rb, re_, snap_r, r_ok.astype(jnp.int32), runs_b, runs_e
    )
    return out > 0


def run_conflicts_xla(rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver) -> jnp.ndarray:
    """XLA fallback with the identical contract: a vmapped full-depth
    lower_bound per run (exact — no convergence fallback needed) plus the
    same rank/neighbour intersection test."""
    from ..ops.search import lex_less, lower_bound

    run_cap = runs_b.shape[1]

    def per_run(bs, es, ver):
        rank = lower_bound(bs, re_)                       # int32[R]
        e_last = jnp.take(es, jnp.clip(rank - 1, 0, run_cap - 1), axis=0)
        intersects = (rank > 0) & lex_less(rb, e_last)
        return intersects & (ver > snap_r)

    conf = jax.vmap(per_run)(runs_b, runs_e, runs_ver)    # [K, R]
    return r_ok & jnp.any(conf, axis=0)


def run_conflicts(rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver,
                  *, impl: str) -> jnp.ndarray:
    """Dispatch on the probed lowering: "tpu" | "interpret" | "xla"."""
    if impl == "xla":
        return run_conflicts_xla(rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver)
    if impl in ("tpu", "interpret"):
        return run_conflicts_pallas(
            rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver,
            interpret=(impl == "interpret"),
        )
    raise ValueError(f"unknown probe impl {impl!r}; choose tpu|interpret|xla")
