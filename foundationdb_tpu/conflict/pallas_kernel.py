"""Pallas sort-scan conflict kernel — the committed-run probe.

The device backend's measured dominator was the committed-write MERGE: the
XLA lowering rewrote the full step function every batch (52.8 of ~57 ms/batch
at CAP=2^19, round-4 profiling).  The incremental redesign (conflict/device.py
"runs" layout) makes the merge an APPEND: each resolved batch's committed
write ranges become one sorted, disjoint interval *run* at a single commit
version, and runs fold into the main step function only at deferred
compactions.  What remains per batch is the check this kernel does — the
sort-scan conflict core:

  for each read range [rb, re) at snapshot `snap`, against each run k:
      conflict  iff  runs_ver[k] > snap          (MVCC version-window check)
                and  run k intersects [rb, re)   (segment-intersection scan)

Because a run's intervals are sorted and DISJOINT, their end keys are sorted
too, so the intersection test collapses to a rank + one neighbour row:

      rank = |{ i : begins[i] < re }|            (sort-merge of the query
                                                  against the run's key order)
      intersects  iff  rank > 0  and  ends[rank-1] > rb

The kernel fuses all three per (run, read-block) grid step: the run's begin
and end key tensors live in VMEM; the rank comes from a two-level scan — a
vectorized lexicographic count against a summary of every STRIDE-th begin
key (the merge-path coarse partition), then a counted compare inside the
one STRIDE-wide window the rank can occupy.  No state-sized scatters, no
HBM gathers: everything a block touches is VMEM-resident, which is exactly
the access pattern XLA's gather/scatter lowering denied us.

Lowering chain (the capability probe, `pallas_mode`):

  * "tpu"        — compiled Pallas on a real TPU backend (the production
                   lowering; shapes here are small enough that Mosaic's
                   (8, 128) tiling pads the W=5..9 lane dimension).
  * "interpret"  — `pl.pallas_call(..., interpret=True)`: the same kernel
                   body run by the Pallas interpreter on CPU.  Slow, but
                   bit-identical — tier-1 parity tests pin the kernel's
                   semantics to the oracle without TPU access.
  * None         — Pallas unavailable (or FDBTPU_PALLAS=off): callers fall
                   back to `run_conflicts_xla`, a vmapped full-depth binary
                   search with the identical contract, so no backend or test
                   ever depends on Pallas being importable.

All-integer and deterministic, like the rest of the conflict core: the probe
is a pure function of (reads, runs), so CPU interpret, XLA fallback and TPU
verdicts agree bit-for-bit.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

READ_BLOCK = 128    # reads per grid step (R is power-of-two bucketed, >= 16)
SUMMARY_STRIDE = 128  # begin keys per summary window (the coarse partition)
I32_MAX = 0x7FFFFFFF  # ops.rmq identity, repeated here so kernel bodies
#                       close over a Python int, not an imported device value


# ---------------------------------------------------------------------------
# capability probe


@functools.lru_cache(maxsize=1)
def pallas_available() -> bool:
    """Can `jax.experimental.pallas` be imported at all?  Cached: the probe
    runs in every DeviceConflictSet constructor."""
    try:
        from jax.experimental import pallas as _pl  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 — any import failure means "no pallas"
        return False


def pallas_mode(override: str | None = None) -> str | None:
    """Resolve the probe lowering: explicit override, else FDBTPU_PALLAS,
    else auto.  Returns "tpu" | "interpret" | None (None => XLA fallback).

    auto: compiled Pallas when the default backend is a TPU, XLA fallback
    otherwise — interpret mode is a *testing* lowering (orders of magnitude
    slower than XLA on CPU) and is never chosen implicitly.  Unknown values
    fail loudly, the knob-parsing convention."""
    v = override or os.environ.get("FDBTPU_PALLAS", "auto")
    if v in ("off", "0", "none"):
        return None
    if not pallas_available():
        if v in ("interpret", "tpu", "on", "1"):
            raise RuntimeError(
                f"FDBTPU_PALLAS={v!r} but jax.experimental.pallas is not importable"
            )
        return None
    if v == "interpret":
        return "interpret"
    if v in ("tpu", "on", "1"):
        return "tpu"
    if v == "auto":
        return "tpu" if jax.default_backend() == "tpu" else None
    raise ValueError(
        f"unknown FDBTPU_PALLAS value {v!r}; choose auto|tpu|interpret|off"
    )


# ---------------------------------------------------------------------------
# shared lexicographic compare (broadcasting twin of ops.search.lex_less,
# usable inside a Pallas kernel body)


def lex_less_b(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a < b over the trailing word axis, broadcasting over
    leading axes (ops.search.lex_less requires equal ranks; kernels compare
    [QB, 1, W] against [1, N, W])."""
    W = a.shape[-1]
    lt = a < b
    eq = a == b
    out = lt[..., W - 1]
    for w in range(W - 2, -1, -1):
        out = lt[..., w] | (eq[..., w] & out)
    return out


# ---------------------------------------------------------------------------
# the kernel


def _block_rank(rows, q, *, stride: int, nrows: int):
    """Two-level rank scan shared by every kernel body here: #rows (sorted,
    VMEM-resident [nrows, W]) lexicographically below each query row q
    ([QB, W]) — a vectorized count against every stride-th row (the
    merge-path coarse partition), then a counted compare inside the one
    stride-wide window the rank can occupy.  Returns int32[QB]."""
    n_sum = nrows // stride
    wins = rows.reshape(n_sum, stride, rows.shape[-1])
    summary = wins[:, 0, :]      # every stride-th key (merge-path posts)

    # coarse scan: rank lives in window (coarse - 1); coarse == 0 means
    # rank == 0 (rows[0] >= q).
    coarse = jnp.sum(
        lex_less_b(summary[None, :, :], q[:, None, :]).astype(jnp.int32),
        axis=1,
    )                            # [QB]
    w_i = jnp.clip(coarse - 1, 0, n_sum - 1)
    window = jnp.take(wins, w_i, axis=0)        # [QB, stride, W]
    fine = jnp.sum(
        lex_less_b(window, q[:, None, :]).astype(jnp.int32), axis=1
    )
    return jnp.where(coarse > 0, w_i * stride + fine, 0)


def _probe_conf(ver, rb, re_, snap, rok, begins, ends, *, stride: int,
                run_cap: int):
    """One run's conflict bits for one read block (the sort-scan core)."""
    rank = _block_rank(begins, re_, stride=stride, nrows=run_cap)
    # ends are sorted (disjoint intervals), so the candidate with the
    # largest end among begins < re is exactly ends[rank - 1]
    e_last = jnp.take(ends, jnp.clip(rank - 1, 0, run_cap - 1), axis=0)
    intersects = (rank > 0) & lex_less_b(rb, e_last)
    return ((rok > 0) & intersects & (ver > snap)).astype(jnp.int32)


def _probe_kernel(ver_ref, rb_ref, re_ref, snap_ref, rok_ref, b_ref, e_ref,
                  out_ref, *, stride: int, run_cap: int):
    """One (read-block, run) grid step of the sort-scan probe.

    Grid is (R // READ_BLOCK, K) with the run axis MINOR, so each read
    block's output is produced by K consecutive steps and accumulated with
    the standard revisiting pattern (init at k == 0, OR afterwards)."""
    from jax.experimental import pallas as pl

    k = pl.program_id(1)
    conf = _probe_conf(
        ver_ref[k], rb_ref[...], re_ref[...], snap_ref[...], rok_ref[...],
        b_ref[0], e_ref[0], stride=stride, run_cap=run_cap,
    )

    @pl.when(k == 0)
    def _init():
        out_ref[...] = conf

    @pl.when(k > 0)
    def _accum():
        out_ref[...] = out_ref[...] | conf


def _probe_fused_kernel(ver_ref, rb_ref, re_ref, snap_ref, rok_ref, hist_ref,
                        b_ref, e_ref, out_ref, *, stride: int, run_cap: int):
    """Fused history + probe grid step: identical sort-scan core, but the
    per-read MAIN-level history bit (range-max vs snapshot, computed by the
    caller) rides the k == 0 init — the combined conflict bits leave the
    grid in one pass instead of a separate txn-level OR."""
    from jax.experimental import pallas as pl

    k = pl.program_id(1)
    conf = _probe_conf(
        ver_ref[k], rb_ref[...], re_ref[...], snap_ref[...], rok_ref[...],
        b_ref[0], e_ref[0], stride=stride, run_cap=run_cap,
    )

    @pl.when(k == 0)
    def _init():
        out_ref[...] = hist_ref[...] | conf

    @pl.when(k > 0)
    def _accum():
        out_ref[...] = out_ref[...] | conf


@functools.lru_cache(maxsize=64)
def _build_probe(K: int, run_cap: int, W: int, R: int, interpret: bool):
    """Compile-cache the pallas_call for one (shape, mode) combo."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    qb = min(READ_BLOCK, R)
    stride = min(SUMMARY_STRIDE, run_cap)
    grid = (R // qb, K)
    kernel = functools.partial(_probe_kernel, stride=stride, run_cap=run_cap)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                    # runs_ver [K]
            pl.BlockSpec((qb, W), lambda q, k: (q, 0)),               # rb
            pl.BlockSpec((qb, W), lambda q, k: (q, 0)),               # re
            pl.BlockSpec((qb,), lambda q, k: (q,)),                   # snap
            pl.BlockSpec((qb,), lambda q, k: (q,)),                   # r_ok
            pl.BlockSpec((1, run_cap, W), lambda q, k: (k, 0, 0)),    # begins
            pl.BlockSpec((1, run_cap, W), lambda q, k: (k, 0, 0)),    # ends
        ],
        out_specs=pl.BlockSpec((qb,), lambda q, k: (q,)),
        out_shape=jax.ShapeDtypeStruct((R,), jnp.int32),
        interpret=interpret,
    )


def run_conflicts_pallas(rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver,
                         *, interpret: bool) -> jnp.ndarray:
    """Pallas lowering of the run probe.  Returns bool[R]: read i conflicts
    with some committed run newer than its snapshot."""
    K, run_cap, W = runs_b.shape
    R = rb.shape[0]
    fn = _build_probe(K, run_cap, W, R, interpret)
    out = fn(
        runs_ver, rb, re_, snap_r, r_ok.astype(jnp.int32), runs_b, runs_e
    )
    return out > 0


def run_conflicts_xla(rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver) -> jnp.ndarray:
    """XLA fallback with the identical contract: a vmapped full-depth
    lower_bound per run (exact — no convergence fallback needed) plus the
    same rank/neighbour intersection test."""
    from ..ops.search import lex_less, lower_bound

    run_cap = runs_b.shape[1]

    def per_run(bs, es, ver):
        rank = lower_bound(bs, re_)                       # int32[R]
        e_last = jnp.take(es, jnp.clip(rank - 1, 0, run_cap - 1), axis=0)
        intersects = (rank > 0) & lex_less(rb, e_last)
        return intersects & (ver > snap_r)

    conf = jax.vmap(per_run)(runs_b, runs_e, runs_ver)    # [K, R]
    return r_ok & jnp.any(conf, axis=0)


def run_conflicts(rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver,
                  *, impl: str) -> jnp.ndarray:
    """Dispatch on the probed lowering: "tpu" | "interpret" | "xla"."""
    if impl == "xla":
        return run_conflicts_xla(rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver)
    if impl in ("tpu", "interpret"):
        return run_conflicts_pallas(
            rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver,
            interpret=(impl == "interpret"),
        )
    raise ValueError(f"unknown probe impl {impl!r}; choose tpu|interpret|xla")


# ---------------------------------------------------------------------------
# fused history + probe: the per-read main-level history bit enters the
# sort-scan grid and ORs into the k == 0 init, so history + run conflicts
# leave the kernel as ONE bit vector (inc_check scatters it to txn level
# exactly once)


@functools.lru_cache(maxsize=64)
def _build_probe_fused(K: int, run_cap: int, W: int, R: int, interpret: bool):
    """Compile-cache the fused pallas_call for one (shape, mode) combo."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    qb = min(READ_BLOCK, R)
    stride = min(SUMMARY_STRIDE, run_cap)
    grid = (R // qb, K)
    kernel = functools.partial(
        _probe_fused_kernel, stride=stride, run_cap=run_cap
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                    # runs_ver [K]
            pl.BlockSpec((qb, W), lambda q, k: (q, 0)),               # rb
            pl.BlockSpec((qb, W), lambda q, k: (q, 0)),               # re
            pl.BlockSpec((qb,), lambda q, k: (q,)),                   # snap
            pl.BlockSpec((qb,), lambda q, k: (q,)),                   # r_ok
            pl.BlockSpec((qb,), lambda q, k: (q,)),                   # hist bits
            pl.BlockSpec((1, run_cap, W), lambda q, k: (k, 0, 0)),    # begins
            pl.BlockSpec((1, run_cap, W), lambda q, k: (k, 0, 0)),    # ends
        ],
        out_specs=pl.BlockSpec((qb,), lambda q, k: (q,)),
        out_shape=jax.ShapeDtypeStruct((R,), jnp.int32),
        interpret=interpret,
    )


def run_conflicts_fused(rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver,
                        hist_r, *, impl: str) -> jnp.ndarray:
    """run_conflicts with the main-level history bit fused in: returns
    bool[R] = hist_r | (r_ok & run-probe conflict).  `hist_r` is the
    caller's per-read "range-max over covered gaps > snapshot" bit (already
    r_ok-masked).  Contractually identical across all three lowerings."""
    if impl == "xla":
        return hist_r | run_conflicts_xla(
            rb, re_, snap_r, r_ok, runs_b, runs_e, runs_ver
        )
    if impl in ("tpu", "interpret"):
        K, run_cap, W = runs_b.shape
        R = rb.shape[0]
        fn = _build_probe_fused(K, run_cap, W, R, impl == "interpret")
        out = fn(
            runs_ver, rb, re_, snap_r, r_ok.astype(jnp.int32),
            hist_r.astype(jnp.int32), runs_b, runs_e,
        )
        return out > 0
    raise ValueError(f"unknown probe impl {impl!r}; choose tpu|interpret|xla")


# ---------------------------------------------------------------------------
# intra min-query kernel: the rank-space fixpoint's per-read reduce
# (device.phase_intra).  Per read r: min over (a) the min-sparse-table of
# writer-begin candidates on rank range (rb_r, re_r) and (b) the stab point
# value at rb_r (write intervals containing the read's begin).  Both tables
# are VMEM-staged whole — n = 2(R+Wn) ints and L*n table entries are a few
# hundred KB at bench shapes.


def _intra_kernel(tab_ref, stab_ref, lo_ref, hi_ref, out_ref, *, n: int):
    """One read-block step: replicate ops.rmq.query_sparse_table's exact
    two-gather semantics (empty range -> I32_MAX) + the stab gather."""
    rbr = lo_ref[...]            # [QB] read-begin ranks
    hi = hi_ref[...]             # [QB] read-end ranks (exclusive)
    tab = tab_ref[...]           # [L, n] min-sparse-table of begin candidates
    stab = stab_ref[...]         # [n] stab of covering-interval candidates
    lo = rbr + 1
    nonempty = hi > lo
    length = jnp.maximum(hi - lo, 1)
    k = jnp.int32(31) - jax.lax.clz(length.astype(jnp.int32))
    pw = jnp.int32(1) << k
    i1 = jnp.clip(lo, 0, n - 1)
    i2 = jnp.clip(hi - pw, 0, n - 1)
    flat = tab.reshape(-1)
    a = jnp.take(flat, k * n + i1)
    b = jnp.take(flat, k * n + i2)
    case1 = jnp.where(nonempty, jnp.minimum(a, b), jnp.int32(I32_MAX))
    case2 = jnp.take(stab, jnp.clip(rbr, 0, n - 1))
    out_ref[...] = jnp.minimum(case1, case2)


@functools.lru_cache(maxsize=64)
def _build_intra(L: int, n: int, R: int, interpret: bool):
    from jax.experimental import pallas as pl

    qb = min(READ_BLOCK, R)
    return pl.pallas_call(
        functools.partial(_intra_kernel, n=n),
        grid=(R // qb,),
        in_specs=[
            pl.BlockSpec((L, n), lambda q: (0, 0)),    # sparse table (VMEM)
            pl.BlockSpec((n,), lambda q: (0,)),        # stab (VMEM)
            pl.BlockSpec((qb,), lambda q: (q,)),       # rb ranks
            pl.BlockSpec((qb,), lambda q: (q,)),       # re ranks
        ],
        out_specs=pl.BlockSpec((qb,), lambda q: (q,)),
        out_shape=jax.ShapeDtypeStruct((R,), jnp.int32),
        interpret=interpret,
    )


def intra_query(beg_tab, stab, rb_r, re_r, *, impl: str) -> jnp.ndarray:
    """minw[r] = min(range-min of beg_tab over (rb_r, re_r), stab[rb_r]) —
    the fused per-read reduce of phase_intra's two-case decomposition.
    Bit-identical to the inline XLA pair (query_sparse_table + take)."""
    if impl not in ("tpu", "interpret"):
        raise ValueError(f"unknown intra impl {impl!r}; choose tpu|interpret")
    L, n = beg_tab.shape
    R = rb_r.shape[0]
    fn = _build_intra(L, n, R, impl == "interpret")
    return fn(beg_tab, stab, rb_r, re_r)


# ---------------------------------------------------------------------------
# run -> step-function interleave (device.run_to_step's Pallas lowering):
# trivially bandwidth-bound, but lowering it keeps the whole deferred-merge
# chain on the same backend as the probe when a compaction fires on-device


_SENT_WORD_P = 0xFFFFFFFF


def _interleave_kernel(ver_ref, b_ref, e_ref, rows_ref, vals_ref, *, W: int):
    ub = b_ref[...]              # [blk, W]
    ue = e_ref[...]              # [blk, W]
    blk = ub.shape[0]
    rows_ref[...] = jnp.stack([ub, ue], axis=1).reshape(2 * blk, W)
    ver = ver_ref[0]
    beg_live = ub[:, W - 1] != jnp.uint32(_SENT_WORD_P)
    v = jnp.where(beg_live, ver, 0).astype(jnp.int32)
    vals_ref[...] = jnp.stack([v, jnp.zeros_like(v)], axis=1).reshape(2 * blk)


@functools.lru_cache(maxsize=64)
def _build_interleave(rcap: int, W: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    blk = min(1024, rcap)
    return pl.pallas_call(
        functools.partial(_interleave_kernel, W=W),
        grid=(rcap // blk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),         # ver [1]
            pl.BlockSpec((blk, W), lambda i: (i, 0)),      # begins
            pl.BlockSpec((blk, W), lambda i: (i, 0)),      # ends
        ],
        out_specs=[
            pl.BlockSpec((2 * blk, W), lambda i: (i, 0)),
            pl.BlockSpec((2 * blk,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((2 * rcap, W), jnp.uint32),
            jax.ShapeDtypeStruct((2 * rcap,), jnp.int32),
        ],
        interpret=interpret,
    )


def run_to_step_pallas(u_b, u_e, ver, *, impl: str):
    """Pallas twin of device.run_to_step: (rows, vals) of the run viewed as
    a step function.  Bit-identical to the XLA interleave."""
    if impl not in ("tpu", "interpret"):
        raise ValueError(f"unknown impl {impl!r}; choose tpu|interpret")
    rcap, W = u_b.shape
    fn = _build_interleave(rcap, W, impl == "interpret")
    ver_arr = jnp.reshape(ver, (1,)).astype(jnp.int32)
    rows, vals = fn(ver_arr, u_b, u_e)
    return rows, vals


# ---------------------------------------------------------------------------
# compact cross-rank kernel: the ONE search the scatter/gather compact folds
# need — ub[j] = #main rows <= rec row j (upper bound via the (words, len+1)
# lane trick, computed by the caller).  Grid is (rec blocks, main blocks)
# with the main axis minor: each step two-level-scans one VMEM-staged main
# block and accumulates the partial rank, so no state-sized gather ever
# leaves HBM row order.


def _rank_count_kernel(q_ref, m_ref, out_ref, *, stride: int, mb: int):
    from jax.experimental import pallas as pl

    m = pl.program_id(1)
    cnt = _block_rank(m_ref[...], q_ref[...], stride=stride, nrows=mb)

    @pl.when(m == 0)
    def _init():
        out_ref[...] = cnt

    @pl.when(m > 0)
    def _accum():
        out_ref[...] = out_ref[...] + cnt


@functools.lru_cache(maxsize=64)
def _build_rank_count(cap: int, rec_cap: int, W: int, interpret: bool):
    from jax.experimental import pallas as pl

    qb = min(READ_BLOCK, rec_cap)
    mb = min(8192, cap)
    stride = min(SUMMARY_STRIDE, mb)
    return pl.pallas_call(
        functools.partial(_rank_count_kernel, stride=stride, mb=mb),
        grid=(rec_cap // qb, cap // mb),
        in_specs=[
            pl.BlockSpec((qb, W), lambda q, m: (q, 0)),    # rec_plus queries
            pl.BlockSpec((mb, W), lambda q, m: (m, 0)),    # main block
        ],
        out_specs=pl.BlockSpec((qb,), lambda q, m: (q,)),
        out_shape=jax.ShapeDtypeStruct((rec_cap,), jnp.int32),
        interpret=interpret,
    )


def compact_ranks(ks, rec_ks, *, impl: str) -> jnp.ndarray:
    """ub[j] = #ks rows lexicographically <= rec_ks[j] — the Pallas lowering
    of device._compact_ub.  Sentinel rec rows rank garbage (their length
    lane wraps); the compact folds mask dead rows, matching the XLA search's
    contract exactly on live rows."""
    if impl not in ("tpu", "interpret"):
        raise ValueError(f"unknown impl {impl!r}; choose tpu|interpret")
    cap, W = ks.shape
    rec_cap = rec_ks.shape[0]
    rec_plus = rec_ks.at[:, -1].add(1)
    fn = _build_rank_count(cap, rec_cap, W, impl == "interpret")
    return fn(rec_plus, ks)
