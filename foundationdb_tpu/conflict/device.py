"""Device (JAX/TPU) ConflictSet — the north-star batched OCC kernel.

TPU-first re-design of the reference resolver's conflict check
(fdbserver/SkipList.cpp `ConflictBatch::detectConflicts`, :1163-1208).  The
reference walks a skip list with a 16-way software-pipelined cursor per read
range and inserts write ranges node-by-node; none of that maps to a systolic
array.  Instead the device keeps the committed-write history as a *step
function* over key space — the same mathematical object the reference's
SlowConflictSet oracle uses (SkipList.cpp:59-88) — stored as fixed-capacity
tensors so every phase is a static-shape vectorized op:

  state:  ks  uint32[CAP, W]   sorted boundary keys (keys.py encoding;
                               sentinel-padded past `count`)
          vs  int32[CAP]       version of the gap [ks[i], ks[i+1]), as an
                               offset from a host-tracked base version

  phase 1 (history check, replaces SkipList::detectConflicts :524):
          per read endpoint: fixed-trip binary search into `ks`; range-max of
          `vs` over the covered gaps via an O(CAP log CAP) sparse table;
          conflict iff max committed version > read snapshot.
  phase 2 (intra-batch, replaces MiniConflictSet :1028-1152):
          the reference's ordered bitmask walk is inherently sequential
          (later txns see earlier *committed* txns' writes).  We solve the
          same recurrence as a fixpoint: start optimistic (everyone
          commits), then repeat "txn t conflicts iff an earlier committed
          txn writes a gap t reads" until unchanged.  Each iteration is a
          vectorized min-scatter (earliest committed writer per endpoint
          gap) + range-min query; the recurrence depends only on earlier
          indices, so the fixpoint is unique and is reached in
          (conflict-chain depth + 1) iterations — a `lax.while_loop`, not a
          10K-step scan.
  phase 3 (insert, replaces mergeWriteConflictRanges :1260):
          merge committed txns' write endpoints into the boundary array by
          merge-path position scatter (no full re-sort), recompute gap
          values ("covered by a committed write ⇒ commit version, else old
          value") via begin/end rank counting, and coalesce equal-valued
          neighbours — which re-compacts the whole state every batch, so
          MVCC GC needs no separate compaction pass.
  GC      (replaces removeBefore :665): versions live as int32 offsets from
          a base that `remove_before` advances; the rebase clamps dead
          versions to 0.  The MVCC window (~5e6 versions ≈ 5s) is far below
          2**31, so offsets never overflow between GCs.

All-integer, no floating point, deterministic: the abort set is a pure
function of the batch, so the jax CPU backend reproduces TPU verdicts
bit-for-bit (simulation parity, SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import keys as keymod
from ..ops.rmq import I32_MAX, build_sparse_table, query_sparse_table, range_update_point_query
from ..ops.search import lower_bound, upper_bound
from .api import ConflictSet, TxInfo, Verdict, validate_batch

_SENT_WORD = np.uint32(0xFFFFFFFF)


def _lexsort_rows(rows: jnp.ndarray) -> jnp.ndarray:
    """Sort uint32[N, W] rows lexicographically; returns sorted rows."""
    order = jnp.lexsort(tuple(rows[:, w] for w in range(rows.shape[1] - 1, -1, -1)))
    return rows[order]


def _is_sentinel(rows: jnp.ndarray) -> jnp.ndarray:
    # Real keys have length-word <= 4*(W-1) < 2**32-1.
    return rows[:, -1] == _SENT_WORD


@functools.partial(jax.jit, donate_argnums=(1,))
def _gc_kernel(ks, vs, off):
    """remove_before: rebase version offsets by `off`, clamping dead gaps to 0."""
    return ks, jnp.maximum(vs - off, 0)


def resolve_core(
    ks,  # uint32[CAP, W] sorted boundaries
    vs,  # int32[CAP] gap version offsets
    rb, re_,  # uint32[R, W] read range begin/end (sentinel rows = padding)
    r_tx,  # int32[R] owning txn index (-1 = padding)
    wb, we,  # uint32[Wn, W] write range begin/end (sentinel rows = padding)
    w_tx,  # int32[Wn]
    snap,  # int32[B] read-snapshot offsets
    active,  # bool[B] False => TOO_OLD (decided host-side at add time)
    commit_off,  # int32 scalar: commit version offset for the whole batch
    *, cap: int, n_txn: int, n_read: int, n_write: int,
):
    """Pure kernel body — jitted directly for the single-partition path and
    called inside shard_map for the multi-resolver path (parallel/sharded.py),
    where each device runs it on its own key partition's clipped ranges."""
    B, R, Wn = n_txn, n_read, n_write

    # ---- phase 1: history conflicts -------------------------------------
    hist_table = build_sparse_table(vs, jnp.maximum, 0)
    g_lo = upper_bound(ks, rb) - 1  # gap containing rb  (ks[0] = b"" <= any key)
    g_hi = lower_bound(ks, re_)  # first boundary >= re
    read_max = query_sparse_table(hist_table, g_lo, g_hi, jnp.maximum, 0)
    r_ok = r_tx >= 0
    r_idx = jnp.clip(r_tx, 0, B - 1)
    r_hist = r_ok & (read_max > snap[r_idx])
    hist = (
        jnp.zeros(B, jnp.int32).at[r_idx].add(r_hist.astype(jnp.int32)) > 0
    )

    # ---- phase 2: intra-batch conflicts (fixpoint) ----------------------
    # Endpoint domain: every range endpoint in the batch, sorted; each range
    # is an exact union of gaps between consecutive endpoints.
    E = 2 * R + 2 * Wn
    ep = _lexsort_rows(jnp.concatenate([rb, re_, wb, we], axis=0))
    r_glo = lower_bound(ep, rb)
    r_ghi = lower_bound(ep, re_)
    w_glo = lower_bound(ep, wb)
    w_ghi = lower_bound(ep, we)
    w_ok = (w_tx >= 0) & ~_is_sentinel(wb)
    w_idx = jnp.clip(w_tx, 0, B - 1)
    tx_iota = jnp.arange(B, dtype=jnp.int32)

    def _body(state):
        intra, _, it = state
        committed = active & ~hist & ~intra
        w_com = w_ok & committed[w_idx]
        # earliest committed writer index per endpoint gap
        min_writer = range_update_point_query(
            E, w_glo, w_ghi, w_tx, w_com, "min", I32_MAX
        )
        mw_table = build_sparse_table(min_writer, jnp.minimum, I32_MAX)
        r_minw = query_sparse_table(mw_table, r_glo, r_ghi, jnp.minimum, I32_MAX)
        r_minw = jnp.where(r_ok, r_minw, I32_MAX)
        tx_minw = jnp.full(B, I32_MAX, jnp.int32).at[r_idx].min(r_minw)
        new_intra = tx_minw < tx_iota  # strictly-earlier committed writer
        changed = jnp.any(new_intra != intra)
        return new_intra, changed, it + 1

    def _cond(state):
        _, changed, it = state
        return changed & (it < B + 2)

    intra0 = jnp.zeros(B, bool)
    intra, _, _ = jax.lax.while_loop(
        _cond, _body, (intra0, jnp.asarray(True), jnp.int32(0))
    )

    committed = active & ~hist & ~intra
    verdict = jnp.where(
        active,
        jnp.where(committed, jnp.int32(Verdict.COMMITTED), jnp.int32(Verdict.CONFLICT)),
        jnp.int32(Verdict.TOO_OLD),
    )

    # ---- phase 3: merge committed writes into the step function ---------
    w_ins = w_ok & committed[w_idx]
    sent_row = jnp.full((ks.shape[1],), _SENT_WORD, jnp.uint32)
    mb = jnp.where(w_ins[:, None], wb, sent_row[None, :])
    me = jnp.where(w_ins[:, None], we, sent_row[None, :])
    sb = _lexsort_rows(mb)  # sorted committed begins (sentinels last)
    se = _lexsort_rows(me)
    news = _lexsort_rows(jnp.concatenate([mb, me], axis=0))  # [2Wn, W]

    M = cap + 2 * Wn
    # merge-path scatter: olds before equal news, stable within each side
    pos_old = jnp.arange(cap, dtype=jnp.int32) + lower_bound(news, ks)
    pos_new = jnp.arange(2 * Wn, dtype=jnp.int32) + upper_bound(ks, news)
    cand = (
        jnp.zeros((M, ks.shape[1]), jnp.uint32)
        .at[pos_old].set(ks)
        .at[pos_new].set(news)
    )
    # gap value at each candidate boundary k: commit_off if k is covered by a
    # committed write range (#begins<=k - #ends<=k > 0), else the old value.
    n_begin = upper_bound(sb, cand)
    n_end = upper_bound(se, cand)
    covered = (n_begin - n_end) > 0
    old_val = vs[jnp.clip(upper_bound(ks, cand) - 1, 0, cap - 1)]
    val = jnp.where(covered, commit_off, old_val)
    # coalesce: keep a boundary iff its value differs from its predecessor's
    # (duplicate keys compute identical values, so dedup falls out too)
    sent = _is_sentinel(cand)
    keep = ~sent & jnp.concatenate([jnp.array([True]), val[1:] != val[:-1]])
    new_count = jnp.sum(keep.astype(jnp.int32))
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    pos = jnp.where(keep, pos, M)  # out-of-range => dropped by scatter
    new_ks = (
        jnp.full((cap, ks.shape[1]), _SENT_WORD, jnp.uint32)
        .at[pos].set(cand, mode="drop")
    )
    new_vs = jnp.zeros(cap, jnp.int32).at[pos].set(val, mode="drop")
    return verdict, new_ks, new_vs, new_count


_resolve_kernel = functools.partial(
    jax.jit, static_argnames=("cap", "n_txn", "n_read", "n_write")
)(resolve_core)


def _bucket(n: int, lo: int = 16) -> int:
    """Round up to a power of two to bound jit recompiles."""
    b = lo
    while b < n:
        b *= 2
    return b


def pack_batch(txns, oldest: int, offset, max_key_bytes: int):
    """Marshal a TxInfo batch into padded device tensors.

    Shared by the single-partition and mesh-sharded conflict sets so their
    TxInfo→tensor encodings cannot drift (verdict parity depends on it).
    `offset` maps an absolute version to the state's int32 offset.
    Returns (rbv, rev, rtv, wbv, wev, wtv, snap, active, bucketed_B).
    """
    B = len(txns)
    W = keymod.num_words(max_key_bytes)
    enc = functools.partial(keymod.encode_keys, max_key_bytes=max_key_bytes)
    active = np.zeros(B, dtype=bool)
    snap = np.zeros(B, dtype=np.int32)
    rb_k: list[bytes] = []
    re_k: list[bytes] = []
    r_tx: list[int] = []
    wb_k: list[bytes] = []
    we_k: list[bytes] = []
    w_tx: list[int] = []
    for t, tx in enumerate(txns):
        if tx.read_snapshot < oldest:
            continue  # TOO_OLD, decided at add time (SkipList.cpp:985)
        active[t] = True
        snap[t] = offset(tx.read_snapshot)
        for b, e in tx.read_ranges:
            if b < e:
                rb_k.append(b)
                re_k.append(e)
                r_tx.append(t)
        for b, e in tx.write_ranges:
            if b < e:
                wb_k.append(b)
                we_k.append(e)
                w_tx.append(t)

    Bp, R, Wn = _bucket(B), _bucket(len(r_tx)), _bucket(len(w_tx))

    def pad(bk, ek, tx, n):
        out_b = np.full((n, W), _SENT_WORD, dtype=np.uint32)
        out_e = np.full((n, W), _SENT_WORD, dtype=np.uint32)
        out_t = np.full(n, -1, dtype=np.int32)
        if bk:
            out_b[: len(bk)] = enc(bk)
            out_e[: len(ek)] = enc(ek)
            out_t[: len(tx)] = tx
        return out_b, out_e, out_t

    rbv, rev, rtv = pad(rb_k, re_k, r_tx, R)
    wbv, wev, wtv = pad(wb_k, we_k, w_tx, Wn)
    snap_p = np.zeros(Bp, dtype=np.int32)
    snap_p[:B] = snap
    active_p = np.zeros(Bp, dtype=bool)
    active_p[:B] = active
    return rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, Bp


class DeviceConflictSet(ConflictSet):
    """ConflictSet backed by the JAX kernel above.

    Runs identically on the TPU backend (production) and the CPU/XLA backend
    (deterministic simulation) — the substitutability that mirrors the
    reference's Net2/Sim2 seam, applied to the device.
    """

    def __init__(
        self,
        oldest_version: int = 0,
        *,
        max_key_bytes: int = keymod.DEFAULT_MAX_KEY_BYTES,
        capacity: int = 1 << 16,
    ) -> None:
        self._max_key_bytes = max_key_bytes
        self._W = keymod.num_words(max_key_bytes)
        self._base = oldest_version
        self._oldest = oldest_version
        self._last_commit = oldest_version
        self._cap = capacity
        self._init_state(capacity)

    def _init_state(self, capacity: int, ks=None, vs=None, count: int = 1) -> None:
        """Fresh state arrays; optionally carry over `count` live boundaries."""
        W = self._W
        nks = np.full((capacity, W), _SENT_WORD, dtype=np.uint32)
        nvs = np.zeros(capacity, dtype=np.int32)
        if ks is None:
            nks[0] = keymod.encode_keys([b""], self._max_key_bytes)[0]
        else:
            nks[:count] = np.asarray(ks)[:count]
            nvs[:count] = np.asarray(vs)[:count]
        self._cap = capacity
        self._ks = jnp.asarray(nks)
        self._vs = jnp.asarray(nvs)
        self._count = count

    @property
    def oldest_version(self) -> int:
        return self._oldest

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def boundary_count(self) -> int:
        return self._count

    def _offset(self, version: int) -> int:
        off = version - self._base
        if off >= 2**31 - 2**24:
            raise OverflowError(
                "version offset overflow: call remove_before to advance the "
                "MVCC window (reference GCs every batch, SkipList.cpp:1199)"
            )
        return max(off, 0)

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        validate_batch(commit_version, txns, self._oldest)
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit_version {commit_version} not after last batch {self._last_commit}"
            )
        B = len(txns)
        if B == 0:
            self._last_commit = commit_version
            return []

        rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, Bp = pack_batch(
            txns, self._oldest, self._offset, self._max_key_bytes
        )
        codes = self.resolve_arrays(
            commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p
        )
        return [Verdict(int(c)) for c in codes[:B]]

    def resolve_arrays(
        self, commit_version: int, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p
    ) -> np.ndarray:
        """Packed fast path: pre-encoded/padded arrays (see pack_batch for the
        layout; snap_p already offset against this set's base).  This is the
        form the resolver role feeds the device — batches arrive packed from
        the proxy, the TxInfo path above is the convenience wrapper."""
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit_version {commit_version} not after last batch {self._last_commit}"
            )
        Bp, R, Wn = snap_p.shape[0], rbv.shape[0], wbv.shape[0]
        while True:
            pre_ks, pre_vs, pre_count = self._ks, self._vs, self._count
            verdict, new_ks, new_vs, new_count = _resolve_kernel(
                self._ks, self._vs,
                rbv, rev, rtv, wbv, wev, wtv,
                snap_p, active_p, np.int32(self._offset(commit_version)),
                cap=self._cap, n_txn=Bp, n_read=R, n_write=Wn,
            )
            new_count = int(new_count)
            if new_count <= self._cap:
                self._ks, self._vs, self._count = new_ks, new_vs, new_count
                self._last_commit = commit_version
                break
            # capacity overflow: the merge dropped boundaries — regrow from
            # the pre-batch state (still valid: the kernel does not donate
            # its inputs) and replay.
            self._init_state(
                max(self._cap * 2, _bucket(new_count)),
                np.asarray(pre_ks), np.asarray(pre_vs), pre_count,
            )
        return np.asarray(verdict)

    def remove_before(self, version: int) -> None:
        if version <= self._oldest:
            return
        self._oldest = version
        off = version - self._base
        if off > 0:
            self._ks, self._vs = _gc_kernel(self._ks, self._vs, np.int32(off))
            self._base = version
