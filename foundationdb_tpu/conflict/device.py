"""Device (JAX/TPU) ConflictSet — the north-star batched OCC kernel.

TPU-first re-design of the reference resolver's conflict check
(fdbserver/SkipList.cpp `ConflictBatch::detectConflicts`, :1163-1208).  The
reference walks a skip list with a 16-way software-pipelined cursor per read
range and inserts write ranges node-by-node; none of that maps to a systolic
array.  Instead the device keeps the committed-write history as a *step
function* over key space — the same mathematical object the reference's
SlowConflictSet oracle uses (SkipList.cpp:59-88) — stored as fixed-capacity
tensors so every phase is a static-shape vectorized op:

  state:  ks  uint32[CAP, W]   sorted boundary keys (keys.py encoding;
                               sentinel-padded past `count`)
          vs  int32[CAP]       version of the gap [ks[i], ks[i+1]), as an
                               offset from a host-tracked base version

  search  ONE bucketed binary search per batch resolves every query class
          at once (read begins as upper-bounds via the (words, len+1) trick,
          read ends, write begins/ends): a uint32[2^16+1] prefix index
          narrows each lower_bound to its word0-prefix bucket, so the fixed
          trip count is ~log2(bucket) ≈ 10 instead of log2(CAP) ≈ 19.
          Row-gathers amortize to ~12ns on TPU when batched; everything
          downstream runs on the returned integer ranks.
  phase 1 (history check, replaces SkipList::detectConflicts :524):
          range-max of `vs` over each read's covered gaps via an
          O(CAP log CAP) sparse table; conflict iff max > read snapshot.
  phase 2 (intra-batch, replaces MiniConflictSet :1028-1152):
          the reference's ordered bitmask walk is inherently sequential
          (later txns see earlier *committed* txns' writes).  Solved as a
          fixpoint over a dense [R, Wn] overlap predicate evaluated in a
          batch-local dense rank space (one lexsort of the batch's
          endpoints): iterate "txn t conflicts iff an earlier committed txn
          writes a range t reads" to convergence — reached in
          (conflict-chain depth + 1) iterations of pure vector compares.
  phase 3 (insert, replaces mergeWriteConflictRanges :1260):
          canonicalize the committed writes' union on the write-endpoint
          slot domain (scatter deltas + cumsum), merge the canonical
          boundaries into the state by merge-path scatter positions derived
          from the ONE search's ranks, recompute gap values with a coverage
          cumsum on the merged domain, and coalesce equal-valued neighbours
          — no additional searches, just scatters and cumsums, which the
          TPU does in ~1ms at 256K elements.
  GC      (replaces removeBefore :665): versions live as int32 offsets from
          a base that `remove_before` advances; the rebase clamps dead
          versions to 0.  The MVCC window (~5e6 versions ≈ 5s) is far below
          2**31, so offsets never overflow between GCs.

All-integer, no floating point, deterministic: the abort set is a pure
function of the batch, so the jax CPU backend reproduces TPU verdicts
bit-for-bit (simulation parity, SURVEY.md §7 "hard parts").

The DEFAULT state layout is now INCREMENTAL (run append + deferred k-way
merge + the Pallas sort-scan probe in conflict/pallas_kernel.py — see the
"Incremental (run-append) state" section below and docs/KERNEL.md); the
per-batch full-state merge documented above remains the automatic fallback
(FDBTPU_INCREMENTAL=0) and the insert path used at compaction time.
"""

from __future__ import annotations

import functools
import time
from itertools import chain
from operator import attrgetter
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import keys as keymod
from ..ops.rmq import (
    I32_MAX,
    _levels,
    build_sparse_table,
    query_sparse_table,
    range_update_point_query,
)
from ..ops.search import lex_less
from . import pallas_kernel
from .api import ConflictSet, KernelStats, TxInfo, Verdict, validate_batch
from .pipeline import PipelinedConflictMixin
from ..runtime.coverage import testcov

_SENT_WORD = np.uint32(0xFFFFFFFF)


def _is_sentinel(rows: jnp.ndarray) -> jnp.ndarray:
    # Real keys have length-word <= 4*(W-1) < 2**32-1.
    return rows[:, -1] == _SENT_WORD


@functools.partial(jax.jit, donate_argnums=(1,))
def _gc_kernel(ks, vs, off):
    """remove_before: rebase version offsets by `off`, clamping dead gaps to 0."""
    return ks, jnp.maximum(vs - off, 0)


BUCKET_BITS = 16
N_BUCKETS = 1 << BUCKET_BITS
FAST_SEARCH_ITERS = 11  # converges windows up to 1024 boundaries (2**(n-1))


def _rec_search_iters() -> int:
    """Bucketed-search depth for the LSM RECENT level (FDBTPU_REC_ITERS).
    The recent level holds ~2^17 boundaries across 2^16 prefix buckets —
    average depth ~2 — so far fewer rounds than FAST_SEARCH_ITERS converge
    it.  A too-shallow setting costs the (tested) full-depth replay
    fallback per affected batch in sync mode, and invalidates a pipelined
    stream (check_pipelined raises; the caller replays through sync) —
    a perf lever, never a correctness one.  Clamped to [1, 32]; malformed
    values fail loudly at construction (the knob-parsing convention).
    Default stays FAST_SEARCH_ITERS until measured on the chip."""
    import os

    v = os.environ.get("FDBTPU_REC_ITERS", str(FAST_SEARCH_ITERS))
    try:
        n = int(v)
    except ValueError:
        raise ValueError(
            f"FDBTPU_REC_ITERS must be an integer, got {v!r}"
        ) from None
    return max(1, min(n, 32))

_IMPL_CHOICES = {"search": ("bucket", "sort"), "merge": ("scatter", "sort", "gather")}
# literal env names (never f-string-built) so grep and flowlint's
# knob-env-sync census can see every FDBTPU_* use
_IMPL_ENV = {"search": "FDBTPU_SEARCH_IMPL", "merge": "FDBTPU_MERGE_IMPL"}


_IMPL_DEFAULTS = {"search": "sort", "merge": "scatter"}


def impl_from_env(kind: str, override: str | None = None) -> str:
    """Resolve the search/merge implementation choice: explicit override,
    else FDBTPU_{KIND}_IMPL, else the measured per-kind default.  Merge
    defaults to "scatter": the PR-16 shootout (.bench_state/probe.log)
    measured the scatter merge 2.4-3.7x faster than the shipped sort merge
    at bench shapes (recent 2^17: 130.9->55.3 ms, main 2^19:
    671.3->179.2 ms), so the measured winner ships as the default and
    sort/gather stay behind FDBTPU_MERGE_IMPL as parity referees and an
    autotune dimension.  A single source of truth so the device, sharded
    and bench paths cannot drift; unknown values fail loudly."""
    import os

    v = override or os.environ.get(_IMPL_ENV[kind], _IMPL_DEFAULTS[kind])
    if v not in _IMPL_CHOICES[kind]:
        raise ValueError(
            f"unknown {kind}_impl {v!r}; choose one of {_IMPL_CHOICES[kind]}"
        )
    return v


def host_bucket_index(ks_rows: np.ndarray) -> np.ndarray:
    """word0-prefix bucket index of sorted boundary rows, host-side (the np
    twin of phase_merge step 3d; sentinels land in the last bucket).  Single
    source of truth for every host construction site."""
    h = (np.asarray(ks_rows)[:, 0] >> BUCKET_BITS).astype(np.int64)
    return np.cumsum(np.bincount(h + 1, minlength=N_BUCKETS + 1))[
        : N_BUCKETS + 1
    ].astype(np.int32)


def _local_ranks(rows: jnp.ndarray) -> jnp.ndarray:
    """Dense order ranks of uint32[N, W] rows: equal rows share a rank and
    strict rank order == strict lexicographic order.  One sort + cumsum —
    the batch-local total order that phases 2/3 run their integer
    comparisons in (full multiword compares happen only in the state
    search)."""
    n, W = rows.shape
    perm = jnp.lexsort(tuple(rows[:, w] for w in range(W - 1, -1, -1)))
    srt = rows[perm]
    first = jnp.concatenate(
        [jnp.array([True]), jnp.any(srt[1:] != srt[:-1], axis=1)]
    )
    rank_sorted = jnp.cumsum(first.astype(jnp.int32)) - 1
    return jnp.zeros(n, jnp.int32).at[perm].set(rank_sorted)


def _bucketed_lower_bound(ks, bucket_idx, count, q, iters: int):
    """lower_bound of q rows into ks, binary-searching only inside the
    16-bit-prefix bucket window (exact: every boundary outside the window is
    strictly below/above q), clamped to the live prefix [0, count) — real
    queries never land among the sentinel padding, so the last bucket
    (sentinels share prefix 0xFFFF) stays shallow.
    Returns (ranks, converged_mask)."""
    n = ks.shape[0]
    if iters >= _levels(n):
        lo = jnp.zeros(q.shape[0], jnp.int32)
        hi = jnp.full(q.shape[0], n, jnp.int32)
    else:
        h = (q[:, 0] >> BUCKET_BITS).astype(jnp.int32)
        lo = jnp.minimum(bucket_idx[h], count)
        hi = jnp.minimum(bucket_idx[h + 1], count)

    def body(_, st):
        lo, hi = st
        active_q = lo < hi
        mid = jnp.clip((lo + hi) // 2, 0, n - 1)
        km = jnp.take(ks, mid, axis=0)
        right = lex_less(km, q)
        lo = jnp.where(active_q & right, mid + 1, lo)
        hi = jnp.where(active_q & ~right, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, iters, body, (lo, hi))
    return lo, lo >= hi


def phase_search(ks, bucket_idx, count, rb, re_, wb, we, r_ok, w_ok,
                 search_iters: int):
    """The ONE state search: every query class concatenated into a single
    bucketed lower_bound (upper_bound(ks, k) == lower_bound(ks, (words,
    len+1)): no key can sit strictly between (w, len) and (w, len+1) in the
    lane encoding).  Returns (g_lo, g_hi, wb_rank, we_rank, converged)."""
    R, Wn = rb.shape[0], wb.shape[0]
    rb_plus = rb.at[:, -1].add(1)
    queries = jnp.concatenate([rb_plus, re_, wb, we], axis=0)
    q_live = jnp.concatenate([r_ok, r_ok, w_ok, w_ok])
    ranks, conv = _bucketed_lower_bound(ks, bucket_idx, count, queries, search_iters)
    converged = ~jnp.any(q_live & ~conv)
    g_lo = ranks[:R] - 1          # gap containing rb (ks[0]="" <= any key)
    g_hi = ranks[R : 2 * R]       # first boundary >= re
    wb_rank = ranks[2 * R : 2 * R + Wn]
    we_rank = ranks[2 * R + Wn :]
    return g_lo, g_hi, wb_rank, we_rank, converged


def phase_search_sort(ks, count, rb, re_, wb, we, r_ok, w_ok):
    """Sort-based twin of phase_search: ranks every query against the state
    with ONE multi-key sort instead of log-depth row gathers (TPU gathers
    lower to serial per-row loops; lax.sort is a tuned network).

    lower_bound(q) = #state keys < q = (sorted position of q) - (#queries
    before q in the sorted order), with queries ordered BEFORE equal state
    keys (flag 0 vs 1) so equal keys are not counted.  Exact at any depth —
    no convergence fallback.  Returns (g_lo, g_hi, wb_rank, we_rank,
    converged=True)."""
    R, Wn = rb.shape[0], wb.shape[0]
    W = ks.shape[1]
    cap = ks.shape[0]
    rb_plus = rb.at[:, -1].add(1)
    queries = jnp.concatenate([rb_plus, re_, wb, we], axis=0)
    nq = queries.shape[0]
    # sentinel-pad the state past `count` is already true of ks; sentinel
    # queries (padding rows) rank among the sentinels — discarded by *_ok
    rows = jnp.concatenate([queries, ks], axis=0)
    flag = jnp.concatenate([jnp.zeros(nq, jnp.uint32), jnp.ones(cap, jnp.uint32)])
    idx = jnp.concatenate(
        [jnp.arange(nq, dtype=jnp.int32), jnp.full(cap, -1, jnp.int32)]
    )
    ops = tuple(rows[:, w] for w in range(W)) + (flag, idx)
    srt = jax.lax.sort(ops, num_keys=W + 1)
    sidx = srt[W + 1]
    is_q = sidx >= 0
    pos = jnp.arange(nq + cap, dtype=jnp.int32)
    n_q_before = jnp.cumsum(is_q.astype(jnp.int32)) - is_q.astype(jnp.int32)
    state_rank = pos - n_q_before
    # clamp into the live prefix (sentinel-region ranks exceed count)
    state_rank = jnp.minimum(state_rank, count)
    ranks = jnp.zeros(nq, jnp.int32).at[
        jnp.where(is_q, sidx, nq)
    ].set(jnp.where(is_q, state_rank, 0), mode="drop")
    g_lo = ranks[:R] - 1
    g_hi = ranks[R : 2 * R]
    wb_rank = ranks[2 * R : 2 * R + Wn]
    we_rank = ranks[2 * R + Wn :]
    return g_lo, g_hi, wb_rank, we_rank, jnp.asarray(True)


def phase_history(vs, g_lo, g_hi, snap, r_idx, r_ok, n_txn: int):
    """History conflicts (replaces SkipList::detectConflicts :524):
    range-max of `vs` over each read's covered gaps; conflict iff
    max > read snapshot."""
    hist_table = build_sparse_table(vs, jnp.maximum, 0)
    read_max = query_sparse_table(hist_table, g_lo, g_hi, jnp.maximum, 0)
    r_hist = r_ok & (read_max > snap[r_idx])
    return jnp.zeros(n_txn, jnp.int32).at[r_idx].add(r_hist.astype(jnp.int32)) > 0


def phase_intra_dense(rb, re_, wb, we, r_ok, w_ok, r_idx, w_idx, w_tx, active,
                      hist, n_txn: int):
    """Dense-referee intra fixpoint (the pre-rank-space formulation): the
    [R, Wn] overlap predicate recomputed inside the reduce each iteration.
    O(R*Wn) per iteration — the measured 527.9 ms/batch dominator at bench
    shapes (.bench_state/probe.log) — kept as the parity referee for
    phase_intra below, which evaluates the identical per-iteration map in
    rank space.  Returns (intra, n_iters)."""
    B, R, Wn = n_txn, rb.shape[0], wb.shape[0]
    lr = _local_ranks(jnp.concatenate([rb, re_, wb, we], axis=0))
    rb_r, re_r = lr[:R], lr[R : 2 * R]
    wb_r, we_r = lr[2 * R : 2 * R + Wn], lr[2 * R + Wn :]
    tx_iota = jnp.arange(B, dtype=jnp.int32)

    def _body(state):
        intra, _, it = state
        committed = active & ~hist & ~intra
        w_com = w_ok & committed[w_idx]
        w_cand = jnp.where(w_com, w_tx, I32_MAX)  # [Wn]
        ov = (wb_r[None, :] < re_r[:, None]) & (rb_r[:, None] < we_r[None, :])
        minw = jnp.min(
            jnp.where(ov, w_cand[None, :], I32_MAX), axis=1
        )  # earliest committed writer overlapping each read
        minw = jnp.where(r_ok, minw, I32_MAX)
        tx_minw = jnp.full(B, I32_MAX, jnp.int32).at[r_idx].min(minw)
        new_intra = tx_minw < tx_iota  # strictly-earlier committed writer
        changed = jnp.any(new_intra != intra)
        return new_intra, changed, it + 1

    def _cond(state):
        _, changed, it = state
        return changed & (it < B + 2)

    intra, _, n_iters = jax.lax.while_loop(
        _cond, _body, (jnp.zeros(B, bool), jnp.asarray(True), jnp.int32(0))
    )
    return intra, n_iters


def phase_intra(rb, re_, wb, we, r_ok, w_ok, r_idx, w_idx, w_tx, active,
                hist, n_txn: int, impl: str = "xla"):
    """Intra-batch conflicts (replaces MiniConflictSet :1028-1152), in RANK
    space.  Same fixpoint as phase_intra_dense — per iteration, minw(r) =
    min txn index over committed writers overlapping read r, then "txn t
    conflicts iff a strictly earlier committed txn writes a range t reads"
    — but the overlap reduce is evaluated against the batch-local endpoint
    ranks instead of a dense [R, Wn] predicate:

      * all 2R+2Wn endpoints rank once (`_local_ranks`, one lexsort);
        live ranges are non-empty (the pack paths drop b >= e), so
        overlap (wb < re and rb < we) partitions EXACTLY by where the
        writer begins relative to the read:
      * case 1 — rb_r < wb_r < re_r (writer begins strictly inside the
        read): a min-sparse-table over writer begins answers the range-min
        on ranks (rb_r, re_r) per read;
      * case 2 — wb_r <= rb_r < we_r (writer covers the read's begin): a
        block-decomposition stab (ops/rmq.py range_update_point_query)
        answers the min over write intervals containing rank rb_r.

    minw = min(case1, case2) is elementwise equal to the dense reduce, so
    the fixpoint trajectory, iteration count and verdicts are BIT-IDENTICAL
    to the referee (pinned in tests/test_pallas.py).  Per-iteration cost is
    O(n log n) scans/scatters with n = 2R+2Wn instead of the dense R*Wn —
    the measured 527.9 ms/batch at bench shapes drops to sparse-table
    build + stab cost (docs/KERNEL.md has the before/after table).

    `impl`: "xla" (default) evaluates the two queries inline; "tpu" /
    "interpret" routes the per-read min query through the fused Pallas
    kernel (conflict/pallas_kernel.py intra_query) with explicit VMEM
    staging of the rank tables — the same capability probe as the run
    probe.  Returns (intra, n_iters)."""
    B, R, Wn = n_txn, rb.shape[0], wb.shape[0]
    lr = _local_ranks(jnp.concatenate([rb, re_, wb, we], axis=0))
    rb_r, re_r = lr[:R], lr[R : 2 * R]
    wb_r, we_r = lr[2 * R : 2 * R + Wn], lr[2 * R + Wn :]
    n = 2 * (R + Wn)
    tx_iota = jnp.arange(B, dtype=jnp.int32)
    # non-empty in rank space; also guards the stab against inverted rows
    w_span = wb_r < we_r

    def _body(state):
        intra, _, it = state
        committed = active & ~hist & ~intra
        w_com = w_ok & committed[w_idx]
        w_cand = jnp.where(w_com, w_tx, I32_MAX)  # [Wn]
        # case 1: min candidate txn at each begin rank (idempotent min —
        # duplicate begin ranks collapse; dead writers carry I32_MAX)
        begs = jnp.full(n, I32_MAX, jnp.int32).at[wb_r].min(w_cand)
        beg_tab = build_sparse_table(begs, jnp.minimum, I32_MAX)
        # case 2: stab structure — point g holds the min candidate over
        # live write intervals [wb_r, we_r) containing g
        stab = range_update_point_query(
            n, wb_r, we_r, w_cand, w_com & w_span, "min", I32_MAX
        )
        if impl == "xla":
            case1 = query_sparse_table(
                beg_tab, rb_r + 1, re_r, jnp.minimum, I32_MAX
            )
            minw = jnp.minimum(case1, jnp.take(stab, rb_r))
        else:
            minw = pallas_kernel.intra_query(
                beg_tab, stab, rb_r, re_r, impl=impl
            )
        minw = jnp.where(r_ok, minw, I32_MAX)
        tx_minw = jnp.full(B, I32_MAX, jnp.int32).at[r_idx].min(minw)
        new_intra = tx_minw < tx_iota  # strictly-earlier committed writer
        changed = jnp.any(new_intra != intra)
        return new_intra, changed, it + 1

    def _cond(state):
        _, changed, it = state
        return changed & (it < B + 2)

    intra, _, n_iters = jax.lax.while_loop(
        _cond, _body, (jnp.zeros(B, bool), jnp.asarray(True), jnp.int32(0))
    )
    return intra, n_iters


def resolve_core(
    ks,  # uint32[CAP, W] sorted boundaries
    vs,  # int32[CAP] gap version offsets
    bucket_idx,  # int32[N_BUCKETS+1] word0-prefix index into ks
    count,  # int32 scalar: live boundary count (sentinels start here)
    rb, re_,  # uint32[R, W] read range begin/end (sentinel rows = padding)
    r_tx,  # int32[R] owning txn index (-1 = padding)
    wb, we,  # uint32[Wn, W] write range begin/end (sentinel rows = padding)
    w_tx,  # int32[Wn]
    snap,  # int32[B] read-snapshot offsets
    active,  # bool[B] False => TOO_OLD (decided host-side at add time)
    commit_off,  # int32 scalar: commit version offset for the whole batch
    ok_in=True,  # bool scalar: validity accumulated across a pipelined stream
    *, cap: int, n_txn: int, n_read: int, n_write: int,
    search_iters: int = FAST_SEARCH_ITERS,
    merge_impl: str = "scatter",  # "scatter" | "sort" (phase_merge twin)
    search_impl: str = "bucket",  # "bucket" | "sort" (phase_search twin)
):
    """Pure kernel body — jitted directly for the single-partition path and
    called inside shard_map for the multi-resolver path (parallel/sharded.py).

    Built for how the TPU actually performs (measured, not assumed):
    batched row-gathers amortize well, sorts and cumsums are cheap, and
    everything else — especially large-Q searches and random gathers — is
    poison.  So the kernel does exactly ONE batched state search per batch
    (all query classes concatenated, restricted to 16-bit-prefix buckets),
    runs the intra-batch check as dense integer compares in a batch-local
    rank space, and rebuilds the state with scatters + cumsums on the merged
    index domain instead of searching it.

    Returns (verdict, new_ks, new_vs, new_count, new_bucket_idx, converged,
    ok); `converged` False means a prefix bucket was deeper than 2**search_iters —
    the host replays the same batch with a full-depth search (pure kernel,
    no donation, so replay is exact)."""
    if merge_impl not in _IMPL_CHOICES["merge"]:
        raise ValueError(f"unknown merge_impl {merge_impl!r}")
    if search_impl not in _IMPL_CHOICES["search"]:
        raise ValueError(f"unknown search_impl {search_impl!r}")
    B = n_txn
    r_ok = r_tx >= 0
    r_idx = jnp.clip(r_tx, 0, B - 1)
    w_ok = (w_tx >= 0) & ~_is_sentinel(wb)
    w_idx = jnp.clip(w_tx, 0, B - 1)

    # ---- the ONE state search ------------------------------------------
    if search_impl == "sort":
        g_lo, g_hi, wb_rank, we_rank, converged = phase_search_sort(
            ks, count, rb, re_, wb, we, r_ok, w_ok
        )
    else:
        g_lo, g_hi, wb_rank, we_rank, converged = phase_search(
            ks, bucket_idx, count, rb, re_, wb, we, r_ok, w_ok, search_iters
        )

    # ---- phase 1: history conflicts ------------------------------------
    hist = phase_history(vs, g_lo, g_hi, snap, r_idx, r_ok, B)

    # ---- phase 2: intra-batch conflicts (dense, rank space) -------------
    intra, _n_iters = phase_intra(
        rb, re_, wb, we, r_ok, w_ok, r_idx, w_idx, w_tx, active, hist, B
    )

    committed = active & ~hist & ~intra
    verdict = jnp.where(
        active,
        jnp.where(committed, jnp.int32(Verdict.COMMITTED), jnp.int32(Verdict.CONFLICT)),
        jnp.int32(Verdict.TOO_OLD),
    )

    # ---- phase 3: merge committed writes into the step function ---------
    w_ins = w_ok & committed[w_idx]
    merge = _MERGE_IMPLS[merge_impl]
    new_ks, new_vs, new_count = merge(
        ks, vs, wb, we, wb_rank, we_rank, w_ins, commit_off, cap=cap
    )
    # the bucket index feeds only the bucketed search: with the sort search
    # selected, skip the cap-sized scatter-add rebuild entirely
    new_bucket_idx = (
        bucket_idx if search_impl == "sort" else _rebuild_buckets(new_ks)
    )

    # validity of THIS batch folded into the stream's accumulator INSIDE the
    # kernel: pipelined callers fetch one scalar per drain instead of paying
    # a host link round trip (or a separate tiny program) per batch
    ok = ok_in & converged & (new_count <= cap)
    return verdict, new_ks, new_vs, new_count, new_bucket_idx, converged, ok


def _canonical_union(ks, vs, wb, we, wb_rank, we_rank, w_ins, *, cap: int):
    """Phase 3a: canonicalize the committed writes' union on the
    write-endpoint slot domain (slots = unique write endpoint keys, in key
    order).  Returns (u_rows, u_rank, is_beg, is_end, news_mask,
    resume_val) — shared by both merge implementations."""
    Wn, W = wb.shape
    wlr = _local_ranks(jnp.concatenate([wb, we], axis=0))  # [2Wn] slot ids
    s_b, s_e = wlr[:Wn], wlr[Wn:]
    nslots = 2 * Wn
    delta = (
        jnp.zeros(nslots, jnp.int32)
        .at[s_b].add(w_ins.astype(jnp.int32))
        .at[s_e].add(-w_ins.astype(jnp.int32))
    )
    cov = jnp.cumsum(delta) > 0            # slot s's gap covered?
    prev_cov = jnp.concatenate([jnp.array([False]), cov[:-1]])
    is_beg = cov & ~prev_cov               # canonical interval opens at slot
    is_end = ~cov & prev_cov               # closes at slot
    # slot -> representative row + state rank (duplicates write equal values)
    sent_row = jnp.full((W,), _SENT_WORD, jnp.uint32)
    wrows = jnp.concatenate([wb, we], axis=0)
    wranks = jnp.concatenate([wb_rank, we_rank])
    wmask = jnp.concatenate([w_ins, w_ins])
    u_rows = (
        jnp.broadcast_to(sent_row, (nslots, W)).astype(jnp.uint32)
        .at[jnp.where(wmask, wlr, nslots)].set(wrows, mode="drop")
    )
    u_rank = jnp.zeros(nslots, jnp.int32).at[jnp.where(wmask, wlr, nslots)].set(
        wranks, mode="drop"
    )
    news_mask = is_beg | is_end
    # resume value at a canonical end: the current value AT that key —
    # vs[u_rank] if the key is an existing boundary, else vs[u_rank - 1]
    ks_at = jnp.take(ks, jnp.clip(u_rank, 0, cap - 1), axis=0)
    key_exists = jnp.all(ks_at == u_rows, axis=1)
    resume_idx = jnp.clip(jnp.where(key_exists, u_rank, u_rank - 1), 0, cap - 1)
    resume_val = vs[resume_idx]
    return u_rows, u_rank, is_beg, is_end, news_mask, resume_val


def _rebuild_buckets(new_ks):
    """Phase 3d: word0-prefix bucket index (sentinels land in the last
    bucket; bucket_idx[h] = lower_bound of prefix h, bucket_idx[-1] = cap)."""
    h_all = (new_ks[:, 0] >> BUCKET_BITS).astype(jnp.int32)
    hist_b = jnp.zeros(N_BUCKETS + 1, jnp.int32).at[h_all + 1].add(1)
    return jnp.cumsum(hist_b)


def phase_merge_sort(ks, vs, wb, we, wb_rank, we_rank, w_ins, commit_off, *, cap: int):
    """Sort-based insert (the scatter-free twin of phase_merge): TPU scatters
    and large gathers lower to serial per-row loops (~1us/row — seconds at
    these shapes), while lax.sort is a tuned bitonic network.  So the merge
    is TWO sorts instead of five M-sized scatters:

      sort 1  (W key words + a news-first tiebreak): state rows and the
              canonical new boundaries into one ordered domain; coverage
              deltas and gap values ride along as payloads, then the same
              cumsum/coalesce logic as the scatter path runs elementwise.
      sort 2  (1-bit key, stable): compaction — kept rows to the front,
              dropped rows (masked to sentinels) to the back, then a STATIC
              [:cap] slice is the new state.  No scatter anywhere.

    Returns (new_ks, new_vs, new_count)."""
    Wn, W = wb.shape
    u_rows, u_rank, is_beg, is_end, news_mask, resume_val = _canonical_union(
        ks, vs, wb, we, wb_rank, we_rank, w_ins, cap=cap
    )
    nslots = 2 * Wn
    sent_row = jnp.full((W,), _SENT_WORD, jnp.uint32)

    # ---- sort 1: ordered merge of olds and news ------------------------
    news_rows = jnp.where(news_mask[:, None], u_rows, sent_row[None, :])
    rows = jnp.concatenate([news_rows, ks], axis=0)          # [M, W]
    # news-first on equal keys, so an old boundary's coverage cumsum sees
    # every equal-key transition (same ordering contract as the merge path)
    flag = jnp.concatenate(
        [jnp.zeros(nslots, jnp.uint32), jnp.ones(cap, jnp.uint32)]
    )
    mdelta = jnp.concatenate(
        [
            jnp.where(news_mask, jnp.where(is_beg, 1, -1), 0).astype(jnp.int32),
            jnp.zeros(cap, jnp.int32),
        ]
    )
    val_in = jnp.concatenate(
        [jnp.where(is_beg, commit_off, resume_val).astype(jnp.int32), vs]
    )
    ops = tuple(rows[:, w] for w in range(W)) + (flag, mdelta, val_in)
    srt = jax.lax.sort(ops, num_keys=W + 1)
    merged = jnp.stack(srt[:W], axis=1)
    sflag, smdelta, sval = srt[W], srt[W + 1], srt[W + 2]
    mcov = jnp.cumsum(smdelta) > 0
    val = jnp.where((sflag == 1) & mcov, commit_off, sval)

    # ---- coalesce + compaction via sort 2 ------------------------------
    sent = _is_sentinel(merged)
    keep = ~sent & jnp.concatenate([jnp.array([True]), val[1:] != val[:-1]])
    new_count = jnp.sum(keep.astype(jnp.int32))
    rows2 = jnp.where(keep[:, None], merged, sent_row[None, :])
    val2 = jnp.where(keep, val, 0)
    ops2 = ((~keep).astype(jnp.uint32),) + tuple(
        rows2[:, w] for w in range(W)
    ) + (val2,)
    srt2 = jax.lax.sort(ops2, num_keys=1, is_stable=True)
    new_ks = jnp.stack(srt2[1 : 1 + W], axis=1)[:cap]
    new_vs = srt2[1 + W][:cap]
    return new_ks, new_vs, new_count


def _union_sorted(ks, vs, wb, we, wb_rank, we_rank, w_ins, *, cap: int):
    """Element-domain union of the committed writes, produced SORTED with a
    single 2Wn-row sort and ZERO scatters (the scatter-free twin of
    _canonical_union, for TPU where scatters serialize per row).

    Instead of canonical unique slots, every endpoint is its own element:
    one sort (key words + a begins-before-ends tiebreak) orders them, a
    coverage cumsum finds the 0<->+ transitions, and those transition
    elements ARE the canonical boundaries (duplicates and interior
    endpoints get no marks; equal-key end+begin pairs cancel through,
    exactly the canonical union's net-delta-zero behavior).

    Returns (u_rows sorted, u_rank, is_beg, news_mask, resume_val)."""
    Wn, W = wb.shape
    live = jnp.concatenate([w_ins, w_ins])
    rows = jnp.concatenate([wb, we], axis=0)
    sent_row = jnp.full((W,), _SENT_WORD, jnp.uint32)
    # non-inserted rows to the sentinel region: they must not interleave
    # with live equal keys (their delta is 0 but order could split a group)
    rows = jnp.where(live[:, None], rows, sent_row[None, :])
    tie = jnp.concatenate(
        [jnp.zeros(Wn, jnp.uint32), jnp.ones(Wn, jnp.uint32)]
    )
    ranks = jnp.concatenate([wb_rank, we_rank])
    delta = jnp.where(
        live, jnp.concatenate([jnp.ones(Wn, jnp.int32), jnp.full(Wn, -1, jnp.int32)]), 0
    )
    ops = tuple(rows[:, w] for w in range(W)) + (tie, ranks, delta)
    srt = jax.lax.sort(ops, num_keys=W + 1)
    u_rows = jnp.stack(srt[:W], axis=1)
    u_rank = srt[W + 1]
    sdelta = srt[W + 2]
    cov = jnp.cumsum(sdelta)
    prev = jnp.concatenate([jnp.zeros(1, jnp.int32), cov[:-1]])
    is_beg = (cov > 0) & (prev <= 0)
    news_mask = is_beg | ((cov <= 0) & (prev > 0))
    # resume value at an end boundary: the pre-state value AT that key
    ks_at = jnp.take(ks, jnp.clip(u_rank, 0, cap - 1), axis=0)
    key_exists = jnp.all(ks_at == u_rows, axis=1)
    resume_idx = jnp.clip(jnp.where(key_exists, u_rank, u_rank - 1), 0, cap - 1)
    resume_val = jnp.take(vs, resume_idx)
    return u_rows, u_rank, is_beg, news_mask, resume_val


def phase_merge_gather(ks, vs, wb, we, wb_rank, we_rank, w_ins, commit_off, *, cap: int):
    """Gather-formulated insert — no full-state sort (the "sort" twin's
    cost) and no M-sized row scatters (the "scatter" twin's poison): the
    merge positions are already implied by the ONE search's ranks, so the
    output is CONSTRUCTED by gathers:

      pos_new[j] = rank + j     (strictly increasing: news in key order)
      nb[p]      = #news at positions <= p   (one scalar-sort searchsorted)
      out[p]     = is_new ? news[nb-1] : state[p - nb]

    Everything M-sized is a 1-D int32 array or a batched row gather; the
    only row SORT is the 2Wn-element union.  Coalesce/compaction reuses
    the same trick: a stable 1-bit scalar sort yields the kept-row
    permutation, and two cap-row gathers build the final state."""
    Wn, W = wb.shape
    n = 2 * Wn
    u_rows, u_rank, is_beg, news_mask, resume_val = _union_sorted(
        ks, vs, wb, we, wb_rank, we_rank, w_ins, cap=cap
    )
    M = cap + n
    j = jnp.cumsum(news_mask.astype(jnp.int32)) - 1
    # beyond-capacity news (rank == cap) are dropped, not clamped — same
    # contract as phase_merge; they can only sit at the end of key order
    pos_new = jnp.where(news_mask & (u_rank < cap), u_rank + j, M).astype(jnp.int32)
    # news payloads in news order: pos_new is unique below M, so one
    # single-key sort aligns (pos, is_beg, val, source row) by position
    val_new = jnp.where(is_beg, commit_off, resume_val).astype(jnp.int32)
    sp = jax.lax.sort(
        (pos_new, is_beg.astype(jnp.int32), val_new,
         jnp.arange(n, dtype=jnp.int32)),
        num_keys=1,
    )
    s_beg, s_val, s_src = sp[1], sp[2], sp[3]
    nb = jnp.searchsorted(
        sp[0], jnp.arange(M, dtype=jnp.int32), side="right", method="sort"
    ).astype(jnp.int32)
    prev_nb = jnp.concatenate([jnp.zeros(1, jnp.int32), nb[:-1]])
    is_new = nb > prev_nb
    new_src = jnp.clip(nb - 1, 0, n - 1)
    old_idx = jnp.clip(jnp.arange(M, dtype=jnp.int32) - nb, 0, cap - 1)

    g_beg = jnp.take(s_beg, new_src)
    g_val = jnp.take(s_val, new_src)
    g_row = jnp.take(s_src, new_src)          # union row index of the news
    delta_m = jnp.where(is_new, jnp.where(g_beg == 1, 1, -1), 0)
    mcov = jnp.cumsum(delta_m) > 0
    old_val = jnp.take(vs, old_idx)
    old_sent = jnp.take(ks[:, -1], old_idx) == _SENT_WORD
    sent = ~is_new & old_sent
    val = jnp.where(is_new, g_val, jnp.where(mcov, commit_off, old_val))

    keep = ~sent & jnp.concatenate([jnp.array([True]), val[1:] != val[:-1]])
    new_count = jnp.sum(keep.astype(jnp.int32))
    kperm = jax.lax.sort(
        ((~keep).astype(jnp.uint32), jnp.arange(M, dtype=jnp.int32)),
        num_keys=1, is_stable=True,
    )[1][:cap]
    k_isnew = jnp.take(is_new, kperm)
    out_old = jnp.take(ks, jnp.take(old_idx, kperm), axis=0)
    out_new = jnp.take(u_rows, jnp.take(g_row, kperm), axis=0)
    q_live = jnp.arange(cap) < new_count
    sent_row = jnp.full((W,), _SENT_WORD, jnp.uint32)
    new_ks = jnp.where(
        q_live[:, None],
        jnp.where(k_isnew[:, None], out_new, out_old),
        sent_row[None, :],
    )
    new_vs = jnp.where(q_live, jnp.take(val, kperm), 0)
    return new_ks, new_vs, new_count


def phase_merge(ks, vs, wb, we, wb_rank, we_rank, w_ins, commit_off, *, cap: int):
    """Insert committed writes into the step function (replaces
    mergeWriteConflictRanges :1260): canonicalize the committed writes'
    union on the write-endpoint slot domain (scatter deltas + cumsum),
    merge the canonical boundaries into the state by merge-path scatter
    positions derived from the ONE search's ranks, recompute gap values
    with a coverage cumsum on the merged domain, and coalesce equal-valued
    neighbours.  Returns (new_ks, new_vs, new_count, new_bucket_idx)."""
    Wn, W = wb.shape
    u_rows, u_rank, is_beg, is_end, news_mask, resume_val = _canonical_union(
        ks, vs, wb, we, wb_rank, we_rank, w_ins, cap=cap
    )
    sent_row = jnp.full((W,), _SENT_WORD, jnp.uint32)

    # 3b. merge-path positions: news sort before equal olds (so an old
    # boundary's coverage cumsum sees every equal-key transition).
    j = jnp.cumsum(news_mask.astype(jnp.int32)) - 1        # index among news
    M = cap + 2 * Wn
    pos_new = jnp.where(news_mask, u_rank + j, M)          # M => dropped
    # news with u_rank == cap (beyond a full state) sort after every old and
    # must NOT be counted into any old's shift — drop, don't clamp, or the
    # merge positions collide and a boundary is silently overwritten
    cnt = jnp.zeros(cap, jnp.int32).at[
        jnp.where(news_mask & (u_rank < cap), u_rank, cap)
    ].add(1, mode="drop")
    pos_old = jnp.arange(cap, dtype=jnp.int32) + jnp.cumsum(cnt)

    # NOTE: plain scatters, no indices_are_sorted/unique_indices hints —
    # measured on TPU, the hinted lowering was ~20x SLOWER for these shapes
    merged = (
        jnp.full((M, W), _SENT_WORD, jnp.uint32)
        .at[pos_old].set(ks, mode="drop")
        .at[pos_new].set(u_rows, mode="drop")
    )
    # coverage at old slots: +1 at begins, -1 at ends, cumsum over merged
    mdelta = jnp.zeros(M, jnp.int32).at[pos_new].add(
        jnp.where(is_beg, 1, -1), mode="drop"
    )
    mcov = jnp.cumsum(mdelta) > 0
    is_old = jnp.zeros(M, bool).at[pos_old].set(True, mode="drop")
    val = (
        jnp.zeros(M, jnp.int32)
        .at[pos_old].set(vs, mode="drop")
        .at[pos_new].set(jnp.where(is_beg, commit_off, resume_val), mode="drop")
    )
    val = jnp.where(is_old & mcov, commit_off, val)

    # 3c. compact + coalesce equal-valued neighbours
    sent = _is_sentinel(merged)
    keep = ~sent & jnp.concatenate([jnp.array([True]), val[1:] != val[:-1]])
    new_count = jnp.sum(keep.astype(jnp.int32))
    pos = jnp.where(keep, jnp.cumsum(keep.astype(jnp.int32)) - 1, M)
    new_ks = jnp.full((cap, W), _SENT_WORD, jnp.uint32).at[pos].set(merged, mode="drop")
    new_vs = jnp.zeros(cap, jnp.int32).at[pos].set(val, mode="drop")
    return new_ks, new_vs, new_count


_MERGE_IMPLS = {
    "scatter": phase_merge,
    "sort": phase_merge_sort,
    "gather": phase_merge_gather,
}

_resolve_kernel = functools.partial(
    jax.jit,
    static_argnames=(
        "cap", "n_txn", "n_read", "n_write", "search_iters", "merge_impl",
        "search_impl",
    ),
)(resolve_core)


# ---------------------------------------------------------------------------
# Two-level (LSM) state: the per-batch merge cost is the kernel's dominant
# phase on real TPU (the full-capacity sort/scatter rewrite — 52.8 of
# ~57 ms/batch measured at CAP=2^19), so the state splits into
#
#   main    [cap]      — compacted rarely; its RMQ sparse table and prefix
#                        bucket index are CACHED as state (rebuilt only at
#                        compaction, not per batch)
#   recent  [rec_cap]  — a small step function absorbing each batch via the
#                        same sort-merge, at ~rec_cap/cap of the cost
#
# Correctness rests on max-composition: every recent write is newer than
# every main write (recent accumulates strictly after the last compaction),
# so the live version at any key is max(main(k), recent(k)) with recent's
# 0-valued gaps transparent, and the history check is simply
# max(main range-max, recent range-max) > snapshot.  This is the same
# maths the reference's skip list gets from in-place inserts; an LSM levels
# it the way storage engines do, trading a rare O(cap) compaction for a
# per-batch O(rec_cap) merge.


def history_from_table(tab, g_lo, g_hi, snap, r_idx, r_ok, n_txn: int):
    """History conflicts from a PREBUILT sparse table (LSM main level)."""
    read_max = query_sparse_table(tab, g_lo, g_hi, jnp.maximum, 0)
    r_hist = r_ok & (read_max > snap[r_idx])
    return jnp.zeros(n_txn, jnp.int32).at[r_idx].add(r_hist.astype(jnp.int32)) > 0


def resolve_core_lsm(
    ks, vs, hist_tab, bucket_idx, count,          # main level (read-only here)
    rec_ks, rec_vs, rec_bidx, rec_count,          # recent level (merged into)
    rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off,
    ok_in=True,
    *, cap: int, rec_cap: int, n_txn: int, n_read: int, n_write: int,
    search_iters: int = FAST_SEARCH_ITERS,
    rec_iters: int = FAST_SEARCH_ITERS,
    search_impl: str = "bucket",
    merge_impl: str = "scatter",
):
    """LSM twin of resolve_core.  Per batch: read-search on main (cached
    bucket index, or the exact sort twin), full search on recent, history =
    main(table) | recent, intra unchanged, and the committed writes merge
    into RECENT only.  Main is untouched — compact_lsm folds recent down
    when it fills.

    Returns (verdict, rec_ks', rec_vs', rec_bidx', rec_count', converged, ok).
    """
    B = n_txn
    r_ok = r_tx >= 0
    r_idx = jnp.clip(r_tx, 0, B - 1)
    w_ok = (w_tx >= 0) & ~_is_sentinel(wb)
    w_idx = jnp.clip(w_tx, 0, B - 1)
    R = rb.shape[0]

    # ---- main search: reads only (writes never touch main per batch) ----
    if search_impl == "sort":
        g_lo_m, g_hi_m, _wr, _wer, conv_main = phase_search_sort(
            ks, count, rb, re_, wb, we, r_ok, w_ok
        )
    else:
        rb_plus = rb.at[:, -1].add(1)
        m_queries = jnp.concatenate([rb_plus, re_], axis=0)
        m_ranks, m_conv = _bucketed_lower_bound(
            ks, bucket_idx, count, m_queries, search_iters
        )
        m_live = jnp.concatenate([r_ok, r_ok])
        conv_main = ~jnp.any(m_live & ~m_conv)
        g_lo_m = m_ranks[:R] - 1
        g_hi_m = m_ranks[R:]

    # ---- recent search: all query classes (merge needs write ranks) -----
    if search_impl == "sort":
        g_lo_r, g_hi_r, wb_rank, we_rank, conv_rec = phase_search_sort(
            rec_ks, rec_count, rb, re_, wb, we, r_ok, w_ok
        )
    else:
        g_lo_r, g_hi_r, wb_rank, we_rank, conv_rec = phase_search(
            rec_ks, rec_bidx, rec_count, rb, re_, wb, we, r_ok, w_ok, rec_iters
        )

    # ---- history: newest committed write over each read range -----------
    hist = history_from_table(hist_tab, g_lo_m, g_hi_m, snap, r_idx, r_ok, B)
    hist = hist | phase_history(rec_vs, g_lo_r, g_hi_r, snap, r_idx, r_ok, B)

    # ---- intra-batch ----------------------------------------------------
    intra, _n_iters = phase_intra(
        rb, re_, wb, we, r_ok, w_ok, r_idx, w_idx, w_tx, active, hist, B
    )

    committed = active & ~hist & ~intra
    verdict = jnp.where(
        active,
        jnp.where(committed, jnp.int32(Verdict.COMMITTED), jnp.int32(Verdict.CONFLICT)),
        jnp.int32(Verdict.TOO_OLD),
    )

    # ---- merge committed writes into RECENT -----------------------------
    w_ins = w_ok & committed[w_idx]
    merge = _MERGE_IMPLS[merge_impl]
    new_rec_ks, new_rec_vs, new_rec_count = merge(
        rec_ks, rec_vs, wb, we, wb_rank, we_rank, w_ins, commit_off,
        cap=rec_cap,
    )
    # the bucket index feeds only the bucketed search: with the sort search
    # selected, skip the N_BUCKETS-sized scatter rebuild entirely
    new_rec_bidx = (
        rec_bidx if search_impl == "sort" else _rebuild_buckets(new_rec_ks)
    )

    converged = conv_main & conv_rec
    ok = ok_in & converged & (new_rec_count <= rec_cap)
    return verdict, new_rec_ks, new_rec_vs, new_rec_bidx, new_rec_count, converged, ok


def _ffill(defined, vals):
    """Forward-fill vals where defined (log-depth associative scan — no
    gathers; positions before the first defined entry fill with 0)."""

    def op(a, b):
        da, va = a
        db, vb = b
        return da | db, jnp.where(db, vb, va)

    d, v = jax.lax.associative_scan(op, (defined, vals))
    return jnp.where(d, v, 0)


def _compact_fold_sort(ks, vs, rec_ks, rec_vs, *, cap: int):
    """Sort-based fold (the referee): ONE multiword sort of both levels,
    per-source forward-fills (associative scans) to evaluate each step
    function on the merged domain, max-compose, coalesce equal-valued
    neighbours, and compact with a stable 1-bit sort — the same
    scatter-free recipe as phase_merge_sort, generalized to two full step
    functions.  Returns (new_ks, new_vs, new_count)."""
    rec_cap = rec_ks.shape[0]
    W = ks.shape[1]
    M = cap + rec_cap
    rows = jnp.concatenate([ks, rec_ks], axis=0)
    src = jnp.concatenate(
        [jnp.zeros(cap, jnp.uint32), jnp.ones(rec_cap, jnp.uint32)]
    )
    vals = jnp.concatenate([vs, rec_vs])
    ops = tuple(rows[:, w] for w in range(W)) + (src, vals)
    srt = jax.lax.sort(ops, num_keys=W + 1)  # main-first on equal keys
    merged = jnp.stack(srt[:W], axis=1)
    s_src, s_val = srt[W], srt[W + 1]
    main_f = _ffill(s_src == 0, s_val)
    rec_f = _ffill(s_src == 1, s_val)
    val = jnp.maximum(main_f, rec_f)

    sent = _is_sentinel(merged)
    keep = ~sent & jnp.concatenate([jnp.array([True]), val[1:] != val[:-1]])
    new_count = jnp.sum(keep.astype(jnp.int32))
    sent_row = jnp.full((W,), _SENT_WORD, jnp.uint32)
    rows2 = jnp.where(keep[:, None], merged, sent_row[None, :])
    val2 = jnp.where(keep, val, 0)
    ops2 = ((~keep).astype(jnp.uint32),) + tuple(
        rows2[:, w] for w in range(W)
    ) + (val2,)
    srt2 = jax.lax.sort(ops2, num_keys=1, is_stable=True)
    new_ks = jnp.stack(srt2[1 : 1 + W], axis=1)[:cap]
    new_vs = srt2[1 + W][:cap]
    return new_ks, new_vs, new_count


def _compact_ub(ks, rec_ks, *, cap: int):
    """#main rows <= rec_ks[j], per recent row — the cross ranks the
    scatter/gather folds build their merge-path positions from.  ONE
    full-depth binary search of the rec rows into main (the (words, len+1)
    upper-bound trick; exact, no bucket index needed).  Sentinel rec rows
    wrap their length lane and rank garbage — callers mask dead rows."""
    rec_plus = rec_ks.at[:, -1].add(1)
    ub, _ = _bucketed_lower_bound(
        ks, jnp.zeros(1, jnp.int32), jnp.int32(cap), rec_plus, _levels(cap)
    )
    return ub


def _compact_fold_scatter(ks, vs, rec_ks, rec_vs, *, cap: int, ub=None):
    """Scatter-based fold — the ADOPTED default (PR-16 shootout: 2.4-3.7x
    over the sort fold at bench shapes on the measured backend).  Instead
    of sorting cap+rec_cap rows by W+1 keys, ONE binary search ranks the
    recent rows into main (`_compact_ub`, or a Pallas lowering via `ub`),
    merge-path positions come from an arange + cumsum (the phase_merge
    recipe applied to two full step functions), and the merged domain is
    built with plain row scatters.  Value composition (per-source forward
    fill + max) and coalescing are shared with the sort fold, so the
    outputs are bit-identical — pinned by the merge-impl parity sweep.
    Returns (new_ks, new_vs, new_count)."""
    rec_cap = rec_ks.shape[0]
    W = ks.shape[1]
    M = cap + rec_cap
    rec_live = ~_is_sentinel(rec_ks)
    if ub is None:
        ub = _compact_ub(ks, rec_ks, cap=cap)
    # rec row j lands between main rows ub[j]-1 and ub[j] (main-first on
    # equal keys); #rec rows before main row i is a prefix count of ub
    cnt = jnp.zeros(cap, jnp.int32).at[
        jnp.where(rec_live, ub, cap)
    ].add(1, mode="drop")
    pos_main = jnp.arange(cap, dtype=jnp.int32) + jnp.cumsum(cnt)
    pos_rec = jnp.where(
        rec_live, jnp.arange(rec_cap, dtype=jnp.int32) + ub, M
    )
    merged = (
        jnp.full((M, W), _SENT_WORD, jnp.uint32)
        .at[pos_main].set(ks, mode="drop")
        .at[pos_rec].set(rec_ks, mode="drop")
    )
    main_def = jnp.zeros(M, bool).at[pos_main].set(True, mode="drop")
    rec_def = jnp.zeros(M, bool).at[pos_rec].set(True, mode="drop")
    mval = jnp.zeros(M, jnp.int32).at[pos_main].set(vs, mode="drop")
    rval = jnp.zeros(M, jnp.int32).at[pos_rec].set(rec_vs, mode="drop")
    main_f = _ffill(main_def, mval)
    rec_f = _ffill(rec_def, rval)
    val = jnp.maximum(main_f, rec_f)

    sent = _is_sentinel(merged)
    keep = ~sent & jnp.concatenate([jnp.array([True]), val[1:] != val[:-1]])
    new_count = jnp.sum(keep.astype(jnp.int32))
    pos = jnp.where(keep, jnp.cumsum(keep.astype(jnp.int32)) - 1, M)
    new_ks = jnp.full((cap, W), _SENT_WORD, jnp.uint32).at[pos].set(
        merged, mode="drop"
    )
    new_vs = jnp.zeros(cap, jnp.int32).at[pos].set(val, mode="drop")
    return new_ks, new_vs, new_count


def _compact_fold_gather(ks, vs, rec_ks, rec_vs, *, cap: int, ub=None):
    """Gather-formulated fold (the scatter-free/full-sort-free twin, same
    shape as phase_merge_gather): the cross ranks imply every output
    position, so the merged domain is CONSTRUCTED by row gathers — rec
    positions are strictly increasing, one searchsorted recovers "#rec
    rows at merged positions <= p", and compaction reuses the stable
    1-bit scalar sort + gather trick.  Returns (new_ks, new_vs,
    new_count)."""
    rec_cap = rec_ks.shape[0]
    W = ks.shape[1]
    M = cap + rec_cap
    rec_live = ~_is_sentinel(rec_ks)
    if ub is None:
        ub = _compact_ub(ks, rec_ks, cap=cap)
    # dead rec rows (a suffix) pad past M so the domain stays sorted
    pos_rec = jnp.where(
        rec_live,
        jnp.arange(rec_cap, dtype=jnp.int32) + ub,
        M + jnp.arange(rec_cap, dtype=jnp.int32),
    )
    nb = jnp.searchsorted(
        pos_rec, jnp.arange(M, dtype=jnp.int32), side="right", method="sort"
    ).astype(jnp.int32)
    prev_nb = jnp.concatenate([jnp.zeros(1, jnp.int32), nb[:-1]])
    is_rec = nb > prev_nb
    rec_i = jnp.clip(nb - 1, 0, rec_cap - 1)
    main_i_raw = jnp.arange(M, dtype=jnp.int32) - nb
    oob = main_i_raw >= cap        # only past every live row (see fold proof)
    main_i = jnp.clip(main_i_raw, 0, cap - 1)
    row_rec = jnp.take(rec_ks, rec_i, axis=0)
    row_main = jnp.take(ks, main_i, axis=0)
    merged = jnp.where(is_rec[:, None], row_rec, row_main)
    sent = ~is_rec & (oob | (jnp.take(ks[:, -1], main_i) == _SENT_WORD))
    main_f = _ffill(~is_rec, jnp.take(vs, main_i))
    rec_f = _ffill(is_rec, jnp.take(rec_vs, rec_i))
    val = jnp.maximum(main_f, rec_f)

    keep = ~sent & jnp.concatenate([jnp.array([True]), val[1:] != val[:-1]])
    new_count = jnp.sum(keep.astype(jnp.int32))
    kperm = jax.lax.sort(
        ((~keep).astype(jnp.uint32), jnp.arange(M, dtype=jnp.int32)),
        num_keys=1, is_stable=True,
    )[1][:cap]
    q_live = jnp.arange(cap) < new_count
    sel_rec = jnp.take(is_rec, kperm)
    out_rec = jnp.take(rec_ks, jnp.take(rec_i, kperm), axis=0)
    out_main = jnp.take(ks, jnp.take(main_i, kperm), axis=0)
    sent_row = jnp.full((W,), _SENT_WORD, jnp.uint32)
    new_ks = jnp.where(
        q_live[:, None],
        jnp.where(sel_rec[:, None], out_rec, out_main),
        sent_row[None, :],
    )
    new_vs = jnp.where(q_live, jnp.take(val, kperm), 0)
    return new_ks, new_vs, new_count


_COMPACT_FOLDS = {
    "scatter": _compact_fold_scatter,
    "sort": _compact_fold_sort,
    "gather": _compact_fold_gather,
}


def compact_lsm(ks, vs, rec_ks, rec_vs, *, cap: int,
                merge_impl: str = "scatter", lowering: str = "xla"):
    """Fold recent into main — the deferred k-way merge's inner step and
    the LSM compaction.  `merge_impl` selects the fold recipe (scatter is
    the adopted default; sort/gather are the bit-identical parity referees
    behind FDBTPU_MERGE_IMPL).  `lowering` = "tpu" | "interpret" routes the
    cross-rank search through the Pallas rank kernel
    (conflict/pallas_kernel.py compact_ranks) with VMEM-staged key blocks;
    "xla" (default) uses the inline binary search.

    Returns (new_ks, new_vs, new_count, new_bidx, new_tab); if new_count >
    cap the caller must regrow main and re-run (inputs are not donated)."""
    if merge_impl not in _COMPACT_FOLDS:
        raise ValueError(f"unknown merge_impl {merge_impl!r}")
    if merge_impl == "sort":
        new_ks, new_vs, new_count = _compact_fold_sort(
            ks, vs, rec_ks, rec_vs, cap=cap
        )
    else:
        ub = (
            pallas_kernel.compact_ranks(ks, rec_ks, impl=lowering)
            if lowering != "xla"
            else None
        )
        new_ks, new_vs, new_count = _COMPACT_FOLDS[merge_impl](
            ks, vs, rec_ks, rec_vs, cap=cap, ub=ub
        )
    new_bidx = _rebuild_buckets(new_ks)
    new_tab = build_sparse_table(new_vs, jnp.maximum, 0)
    return new_ks, new_vs, new_count, new_bidx, new_tab


_resolve_lsm_kernel = functools.partial(
    jax.jit,
    static_argnames=(
        "cap", "rec_cap", "n_txn", "n_read", "n_write", "search_iters",
        "rec_iters", "search_impl", "merge_impl",
    ),
)(resolve_core_lsm)

_compact_kernel = functools.partial(
    jax.jit, static_argnames=("cap", "merge_impl", "lowering")
)(compact_lsm)


# ---------------------------------------------------------------------------
# Incremental (run-append) state: the per-batch committed-write merge was the
# kernel's measured dominator on TPU (52.8 of ~57 ms/batch, round-4
# profiling) because it rewrote the full step function every batch.  The
# incremental layout makes the merge an APPEND: each batch's committed
# writes become ONE sorted, disjoint interval run at a single commit-version
# offset:
#
#   runs_b/runs_e  uint32[K, RUN_CAP, W]   per-slot interval begins/ends
#                                          (sentinel-padded; ends sorted too
#                                          because intervals are disjoint)
#   runs_ver       int32[K]                commit offset per slot (0 = dead)
#
# The history check gains a run PROBE — the sort-scan conflict kernel in
# conflict/pallas_kernel.py (Pallas on TPU, interpret on CPU for parity,
# vmapped-XLA fallback) — and the deferred k-way merge folds all runs into
# the main step function only when the K slots fill (compact threshold),
# via compact_lsm: each run IS a step function (ver over its intervals, 0
# elsewhere), so the fold is the existing max-compose.


def _union_intervals(wb, we, w_ins, *, run_cap: int,
                     merge_impl: str = "scatter"):
    """Canonical disjoint interval union of the committed writes, compacted
    to the front and sentinel-padded to run_cap — the payload the
    incremental path appends as one run.  ONE 2Wn-row multiword sort finds
    coverage transitions (begins sort before equal ends so adjacent
    intervals coalesce), then the begin/end rows compact via a cumsum +
    row scatter (merge_impl="scatter", the adopted default — the 1-bit
    stable sorts were the sort-scan append's remaining full-width sorts)
    or the original two stable 1-bit sorts (parity referees); pairwise
    aligned by construction (the j-th begin opens the interval the j-th
    end closes).  Returns (u_b, u_e)."""
    Wn, W = wb.shape
    n = 2 * Wn
    sent_row = jnp.full((W,), _SENT_WORD, jnp.uint32)
    live = jnp.concatenate([w_ins, w_ins])
    rows = jnp.concatenate([wb, we], axis=0)
    rows = jnp.where(live[:, None], rows, sent_row[None, :])
    tie = jnp.concatenate(
        [jnp.zeros(Wn, jnp.uint32), jnp.ones(Wn, jnp.uint32)]
    )
    delta = jnp.where(
        live,
        jnp.concatenate([jnp.ones(Wn, jnp.int32), jnp.full(Wn, -1, jnp.int32)]),
        0,
    )
    ops = tuple(rows[:, w] for w in range(W)) + (tie, delta)
    srt = jax.lax.sort(ops, num_keys=W + 1)
    srows = jnp.stack(srt[:W], axis=1)
    cov = jnp.cumsum(srt[W + 1])
    prev = jnp.concatenate([jnp.zeros(1, jnp.int32), cov[:-1]])
    is_beg = (cov > 0) & (prev <= 0)
    is_end = (cov <= 0) & (prev > 0)

    if merge_impl == "sort" or merge_impl == "gather":
        def compact(mask):
            mrows = jnp.where(mask[:, None], srows, sent_row[None, :])
            ops2 = ((~mask).astype(jnp.uint32),) + tuple(
                mrows[:, w] for w in range(W)
            )
            s2 = jax.lax.sort(ops2, num_keys=1, is_stable=True)
            return jnp.stack(s2[1 : 1 + W], axis=1)
    else:
        def compact(mask):
            pos = jnp.where(mask, jnp.cumsum(mask.astype(jnp.int32)) - 1, n)
            return (
                jnp.full((n, W), _SENT_WORD, jnp.uint32)
                .at[pos].set(srows, mode="drop")
            )

    u_b, u_e = compact(is_beg), compact(is_end)
    if n < run_cap:
        pad = jnp.broadcast_to(sent_row, (run_cap - n, W))
        u_b = jnp.concatenate([u_b, pad], axis=0)
        u_e = jnp.concatenate([u_e, pad], axis=0)
    return u_b[:run_cap], u_e[:run_cap]


def inc_search(ks, bucket_idx, count, rb, re_, r_tx,
               *, search_iters: int = FAST_SEARCH_ITERS,
               search_impl: str = "bucket"):
    """Phase "sort": rank the READ queries against the main level.  The
    incremental path never needs write ranks (nothing merges into main per
    batch), so the write query classes are zero-size.  Returns
    (g_lo, g_hi, converged)."""
    W = ks.shape[1]
    r_ok = r_tx >= 0
    empty = jnp.zeros((0, W), jnp.uint32)
    eb = jnp.zeros((0,), bool)
    if search_impl == "sort":
        g_lo, g_hi, _wr, _wer, conv = phase_search_sort(
            ks, count, rb, re_, empty, empty, r_ok, eb
        )
    else:
        g_lo, g_hi, _wr, _wer, conv = phase_search(
            ks, bucket_idx, count, rb, re_, empty, empty, r_ok, eb,
            search_iters,
        )
    return g_lo, g_hi, conv


def inc_check(hist_base, g_lo, g_hi, rb, re_, r_tx, wb, we, w_tx,
              snap, active, runs_b, runs_e, runs_ver,
              *, n_txn: int, probe_impl: str, from_table: bool):
    """Phase "scan": the fused conflict check — main-level history (from
    gap versions or a prebuilt LSM sparse table), the sort-scan run probe
    (pallas_kernel.run_conflicts), and the intra-batch fixpoint.  Returns
    (verdict, w_ins)."""
    B = n_txn
    r_ok = r_tx >= 0
    r_idx = jnp.clip(r_tx, 0, B - 1)
    w_ok = (w_tx >= 0) & ~_is_sentinel(wb)
    w_idx = jnp.clip(w_tx, 0, B - 1)
    # Per-READ history bits instead of a txn-level pre-reduce: the
    # main-level range-max and the run probe fuse into ONE pass over the
    # reads — run_conflicts_fused ORs the history bit inside the sort-scan
    # grid (Pallas) or the vmapped fallback — and the combined bits scatter
    # to txn level exactly once.  Same final bits as phase_history |
    # run-probe (OR of scatters == scatter of ORs).
    tab = (
        hist_base if from_table
        else build_sparse_table(hist_base, jnp.maximum, 0)
    )
    read_max = query_sparse_table(tab, g_lo, g_hi, jnp.maximum, 0)
    hist_r = r_ok & (read_max > snap[r_idx])
    conf_r = pallas_kernel.run_conflicts_fused(
        rb, re_, snap[r_idx], r_ok, runs_b, runs_e, runs_ver, hist_r,
        impl=probe_impl,
    )
    hist = (
        jnp.zeros(B, jnp.int32).at[r_idx].add((r_ok & conf_r).astype(jnp.int32))
        > 0
    )
    # the intra min-queries ride the same capability probe as the run
    # probe: Pallas on TPU, interpret for CPU parity, inline XLA otherwise
    intra, _n_iters = phase_intra(
        rb, re_, wb, we, r_ok, w_ok, r_idx, w_idx, w_tx, active, hist, B,
        impl=probe_impl,
    )
    committed = active & ~hist & ~intra
    verdict = jnp.where(
        active,
        jnp.where(committed, jnp.int32(Verdict.COMMITTED), jnp.int32(Verdict.CONFLICT)),
        jnp.int32(Verdict.TOO_OLD),
    )
    return verdict, w_ok & committed[w_idx]


def inc_append(runs_b, runs_e, runs_ver, slot, wb, we, w_ins, commit_off,
               *, run_cap: int, merge_impl: str = "scatter"):
    """Phase "merge": append this batch's canonical committed union as run
    `slot` — a dynamic-update-slice of O(run_cap) rows, NOT a full-state
    rewrite.  Returns (runs_b', runs_e', runs_ver')."""
    u_b, u_e = _union_intervals(
        wb, we, w_ins, run_cap=run_cap, merge_impl=merge_impl
    )
    new_b = jax.lax.dynamic_update_slice(runs_b, u_b[None], (slot, 0, 0))
    new_e = jax.lax.dynamic_update_slice(runs_e, u_e[None], (slot, 0, 0))
    return new_b, new_e, runs_ver.at[slot].set(commit_off)


def resolve_core_inc(
    ks, vs, bucket_idx, count,
    runs_b, runs_e, runs_ver, slot,
    rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off,
    ok_in=True,
    *, cap: int, run_cap: int, n_txn: int, n_read: int, n_write: int,
    search_iters: int = FAST_SEARCH_ITERS,
    search_impl: str = "bucket",
    probe_impl: str = "xla",
    merge_impl: str = "scatter",
):
    """Incremental twin of resolve_core: main level is READ-ONLY per batch
    (searched for history only), committed writes append as a run, and the
    run probe covers everything main hasn't absorbed yet.  Returns
    (verdict, runs_b', runs_e', runs_ver', converged, ok)."""
    g_lo, g_hi, conv = inc_search(
        ks, bucket_idx, count, rb, re_, r_tx,
        search_iters=search_iters, search_impl=search_impl,
    )
    verdict, w_ins = inc_check(
        vs, g_lo, g_hi, rb, re_, r_tx, wb, we, w_tx, snap, active,
        runs_b, runs_e, runs_ver,
        n_txn=n_txn, probe_impl=probe_impl, from_table=False,
    )
    new_b, new_e, new_ver = inc_append(
        runs_b, runs_e, runs_ver, slot, wb, we, w_ins, commit_off,
        run_cap=run_cap, merge_impl=merge_impl,
    )
    return verdict, new_b, new_e, new_ver, conv, ok_in & conv


def resolve_core_inc_lsm(
    ks, hist_tab, bucket_idx, count,
    runs_b, runs_e, runs_ver, slot,
    rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off,
    ok_in=True,
    *, cap: int, run_cap: int, n_txn: int, n_read: int, n_write: int,
    search_iters: int = FAST_SEARCH_ITERS,
    search_impl: str = "bucket",
    probe_impl: str = "xla",
    merge_impl: str = "scatter",
):
    """LSM twin of resolve_core_inc: main history from the CACHED sparse
    table (rebuilt only at compaction); the run layer plays the recent
    level's role with appends instead of per-batch sort-merges."""
    g_lo, g_hi, conv = inc_search(
        ks, bucket_idx, count, rb, re_, r_tx,
        search_iters=search_iters, search_impl=search_impl,
    )
    verdict, w_ins = inc_check(
        hist_tab, g_lo, g_hi, rb, re_, r_tx, wb, we, w_tx, snap, active,
        runs_b, runs_e, runs_ver,
        n_txn=n_txn, probe_impl=probe_impl, from_table=True,
    )
    new_b, new_e, new_ver = inc_append(
        runs_b, runs_e, runs_ver, slot, wb, we, w_ins, commit_off,
        run_cap=run_cap, merge_impl=merge_impl,
    )
    return verdict, new_b, new_e, new_ver, conv, ok_in & conv


def run_to_step(u_b, u_e, ver, *, impl: str = "xla"):
    """View one run as a step function: boundaries = interleaved begin/end
    keys (sorted, since b_0 < e_0 < b_1 < ...), gap values = ver over the
    run's intervals and 0 elsewhere.  Feeds compact_lsm directly — the
    deferred k-way merge is the existing two-level max-compose, applied
    once per live run at compaction time.  `impl` = "tpu" | "interpret"
    routes the interleave through the Pallas lowering (same capability
    probe as the run probe)."""
    if impl != "xla":
        return pallas_kernel.run_to_step_pallas(u_b, u_e, ver, impl=impl)
    rcap, W = u_b.shape
    rows = jnp.stack([u_b, u_e], axis=1).reshape(2 * rcap, W)
    beg_live = ~_is_sentinel(u_b)
    vals = jnp.stack(
        [
            jnp.where(beg_live, ver, 0).astype(jnp.int32),
            jnp.zeros(rcap, jnp.int32),
        ],
        axis=1,
    ).reshape(2 * rcap)
    return rows, vals


_inc_statics = (
    "cap", "run_cap", "n_txn", "n_read", "n_write", "search_iters",
    "search_impl", "probe_impl", "merge_impl",
)
_resolve_inc_kernel = functools.partial(
    jax.jit, static_argnames=_inc_statics
)(resolve_core_inc)
_resolve_inc_lsm_kernel = functools.partial(
    jax.jit, static_argnames=_inc_statics
)(resolve_core_inc_lsm)

# split-phase twins for FDBTPU_PHASE_TIMING=1: each phase is its own
# dispatch with a completion barrier, so sort/scan/merge wall times are
# individually observable (profiling mode only — the fused kernel stays
# the hot path)
_inc_search_kernel = functools.partial(
    jax.jit, static_argnames=("search_iters", "search_impl")
)(inc_search)
_inc_check_kernel = functools.partial(
    jax.jit, static_argnames=("n_txn", "probe_impl", "from_table")
)(inc_check)
_inc_append_kernel = functools.partial(
    jax.jit, static_argnames=("run_cap", "merge_impl")
)(inc_append)
_run_step_kernel = functools.partial(
    jax.jit, static_argnames=("impl",)
)(run_to_step)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _gc_lsm_kernel(vs, tab, rec_vs, off):
    """remove_before for the LSM levels: range-max commutes with the
    monotone clamp, so the cached sparse table is clamped in place."""
    return (
        jnp.maximum(vs - off, 0),
        jnp.maximum(tab - off, 0),
        jnp.maximum(rec_vs - off, 0),
    )


def _bucket(n: int, lo: int = 16) -> int:
    """Round up to a power of two to bound jit recompiles."""
    b = lo
    while b < n:
        b *= 2
    return b


def pack_batch_loop(txns, oldest: int, offset, max_key_bytes: int,
                    stats=None):
    """Reference (per-transaction, per-range loop) TxInfo marshaller.

    Kept as the parity referee for the vectorized pack_batch below, and as
    its fallback for batches containing over-length keys, whose
    drop-vs-raise semantics need byte-level compares the lane encoding
    cannot represent.  Same contract as pack_batch; `stats` records the
    same encode_s (lane encoding) / pad_s (everything else: the Python
    loops plus padded-array building) split so the two paths' marshalling
    costs are directly comparable.
    """
    t_start = time.perf_counter()
    enc_spent = [0.0]
    B = len(txns)
    W = keymod.num_words(max_key_bytes)

    def enc(keys):
        t0 = time.perf_counter()
        out = keymod.encode_keys(keys, max_key_bytes=max_key_bytes)
        enc_spent[0] += time.perf_counter() - t0
        return out
    active = np.zeros(B, dtype=bool)
    snap = np.zeros(B, dtype=np.int32)
    rb_k: list[bytes] = []
    re_k: list[bytes] = []
    r_tx: list[int] = []
    wb_k: list[bytes] = []
    we_k: list[bytes] = []
    w_tx: list[int] = []
    for t, tx in enumerate(txns):
        if tx.read_snapshot < oldest:
            continue  # TOO_OLD, decided at add time (SkipList.cpp:985)
        active[t] = True
        snap[t] = offset(tx.read_snapshot)
        for b, e in tx.read_ranges:
            if b < e:
                rb_k.append(b)
                re_k.append(e)
                r_tx.append(t)
        for b, e in tx.write_ranges:
            if b < e:
                wb_k.append(b)
                we_k.append(e)
                w_tx.append(t)

    Bp, R, Wn = _bucket(B), _bucket(len(r_tx)), _bucket(len(w_tx))

    def pad(bk, ek, tx, n):
        out_b = np.full((n, W), _SENT_WORD, dtype=np.uint32)
        out_e = np.full((n, W), _SENT_WORD, dtype=np.uint32)
        out_t = np.full(n, -1, dtype=np.int32)
        if bk:
            out_b[: len(bk)] = enc(bk)
            out_e[: len(ek)] = enc(ek)
            out_t[: len(tx)] = tx
        return out_b, out_e, out_t

    rbv, rev, rtv = pad(rb_k, re_k, r_tx, R)
    wbv, wev, wtv = pad(wb_k, we_k, w_tx, Wn)
    snap_p = np.zeros(Bp, dtype=np.int32)
    snap_p[:B] = snap
    active_p = np.zeros(Bp, dtype=bool)
    active_p[:B] = active
    if stats is not None:
        stats.encode_s += enc_spent[0]
        stats.pad_s += time.perf_counter() - t_start - enc_spent[0]
    return rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, Bp


def _np_rows_less(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Rowwise lexicographic a < b over uint32[N, W] lane rows, host-side.
    Faithful to byte-string order for keys within max_key_bytes (keys.py
    module docstring: the length lane breaks zero-padding ties)."""
    neq = a != b
    any_neq = neq.any(axis=1)
    first = neq.argmax(axis=1)
    rows = np.arange(a.shape[0])
    return any_neq & (a[rows, first] < b[rows, first])


def pack_batch(txns, oldest: int, offset, max_key_bytes: int, *,
               arena=None, stats=None, offset_array=None):
    """Marshal a TxInfo batch into padded device tensors — the BULK path.

    Shared by the single-partition and mesh-sharded conflict sets so their
    TxInfo→tensor encodings cannot drift (verdict parity depends on it).
    `offset` maps an absolute version to the state's int32 offset.
    Returns (rbv, rev, rtv, wbv, wev, wtv, snap, active, bucketed_B).

    Bit-identical tensors to pack_batch_loop, produced without per-range
    Python loops: ONE pass flattens every conflict-range endpoint of the
    batch into a single byte stream, ONE keys.encode_concat call encodes
    them all, the b < e liveness filter runs as a vectorized lane compare
    on the encoded rows, and the padded outputs fill preallocated
    staging-arena slots (conflict/pipeline.py PackArena) instead of fresh
    np.full allocations per batch.  Optional hooks:

      arena         PackArena: rotating per-bucket-shape staging buffers
      stats         KernelStats: lands the encode_s / pad_s phase split
      offset_array  vectorized `offset` twin (np array -> np array); when
                    absent, `offset` is called per active transaction in
                    order, exactly like the loop path

    Batches containing a key longer than max_key_bytes delegate to
    pack_batch_loop (encoded-lane compares cannot decide their b < e
    liveness, so the raise-vs-drop semantics live there).
    """
    B = len(txns)
    if B == 0:
        return pack_batch_loop(txns, oldest, offset, max_key_bytes, stats=stats)
    t0 = time.perf_counter()
    W = keymod.num_words(max_key_bytes)
    snaps_raw = np.fromiter(
        (t.read_snapshot for t in txns), dtype=np.int64, count=B
    )
    active = snaps_raw >= oldest
    if active.all():
        act_txns = txns if isinstance(txns, list) else list(txns)
        act_ids = np.arange(B, dtype=np.int32)
    else:  # TOO_OLD txns contribute no ranges (SkipList.cpp:985)
        alist = active.tolist()
        act_txns = [t for t, a in zip(txns, alist) if a]
        act_ids = np.flatnonzero(active).astype(np.int32)
    nA = len(act_txns)
    r_counts = np.fromiter(
        map(len, map(attrgetter("read_ranges"), act_txns)),
        dtype=np.int64, count=nA,
    )
    w_counts = np.fromiter(
        map(len, map(attrgetter("write_ranges"), act_txns)),
        dtype=np.int64, count=nA,
    )
    # flatten [(b0,e0), (b1,e1), ...] across txns into one key stream
    r_keys = list(
        chain.from_iterable(chain.from_iterable(t.read_ranges for t in act_txns))
    )
    w_keys = list(
        chain.from_iterable(chain.from_iterable(t.write_ranges for t in act_txns))
    )
    all_keys = r_keys + w_keys
    n_all = len(all_keys)
    lens = np.fromiter(map(len, all_keys), dtype=np.int64, count=n_all)
    if n_all and int(lens.max()) > max_key_bytes:
        return pack_batch_loop(txns, oldest, offset, max_key_bytes, stats=stats)
    enc = keymod.encode_concat(b"".join(all_keys), lens, max_key_bytes)
    t1 = time.perf_counter()

    nR, nW = len(r_keys) // 2, len(w_keys) // 2
    pairs = enc.reshape(nR + nW, 2, W)
    renc, wenc = pairs[:nR], pairs[nR:]
    r_tx_all = np.repeat(act_ids, r_counts)
    w_tx_all = np.repeat(act_ids, w_counts)
    # ONE vectorized b < e liveness compare over every pair (read + write)
    live = _np_rows_less(pairs[:, 0], pairs[:, 1]) if (nR + nW) else (
        np.zeros(0, dtype=bool)
    )
    all_live = bool(live.all())
    if all_live:
        r_idx = w_idx = None
        n_r, n_w = nR, nW
    else:
        r_idx = np.flatnonzero(live[:nR])
        w_idx = np.flatnonzero(live[nR:])
        n_r, n_w = len(r_idx), len(w_idx)
    Bp, R, Wn = _bucket(B), _bucket(n_r), _bucket(n_w)

    # snapshot offsets, in txn order (the loop path's offset() call order)
    if offset_array is not None:
        snap_vals = offset_array(snaps_raw[active])
    else:
        snap_vals = np.fromiter(
            (offset(int(s)) for s in snaps_raw[active]), dtype=np.int64,
            count=nA,
        )

    def fill_rows(kind: str, n_rows: int, enc3, idx, tx_all, all_live: bool):
        n = enc3.shape[0] if all_live else len(idx)
        if arena is not None:
            slot = arena.rows(kind, n_rows, W, _SENT_WORD)
            hi = slot.live
            if hi > n:  # re-sentinel only the previously-live pad region
                slot.b[n:hi] = _SENT_WORD
                slot.e[n:hi] = _SENT_WORD
                slot.t[n:hi] = -1
            slot.live = n
            out_b, out_e, out_t = slot.b, slot.e, slot.t
        else:
            out_b = np.full((n_rows, W), _SENT_WORD, dtype=np.uint32)
            out_e = np.full((n_rows, W), _SENT_WORD, dtype=np.uint32)
            out_t = np.full(n_rows, -1, dtype=np.int32)
        if n:
            if all_live:  # common case: contiguous copy, no gather
                out_b[:n] = enc3[:, 0]
                out_e[:n] = enc3[:, 1]
                out_t[:n] = tx_all
            else:
                out_b[:n] = enc3[idx, 0]
                out_e[:n] = enc3[idx, 1]
                out_t[:n] = tx_all[idx]
        return out_b, out_e, out_t

    rbv, rev, rtv = fill_rows("r", R, renc, r_idx, r_tx_all, all_live)
    wbv, wev, wtv = fill_rows("w", Wn, wenc, w_idx, w_tx_all, all_live)
    if arena is not None:
        ts = arena.txns(Bp)
        hi = ts.live
        if hi > B:
            ts.snap[B:hi] = 0
            ts.active[B:hi] = False
        ts.live = B
        snap_p, active_p = ts.snap, ts.active
    else:
        snap_p = np.zeros(Bp, dtype=np.int32)
        active_p = np.zeros(Bp, dtype=bool)
    if nA == B:
        snap_p[:B] = snap_vals
    else:
        snap_p[:B] = 0
        snap_p[act_ids] = snap_vals
    active_p[:B] = active
    if stats is not None:
        t2 = time.perf_counter()
        stats.encode_s += t1 - t0
        stats.pad_s += t2 - t1
    return rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, Bp


class DeviceConflictSet(PipelinedConflictMixin, ConflictSet):
    """ConflictSet backed by the JAX kernel above.

    Runs identically on the TPU backend (production) and the CPU/XLA backend
    (deterministic simulation) — the substitutability that mirrors the
    reference's Net2/Sim2 seam, applied to the device.

    resolve_deferred (conflict/pipeline.py) adds the split-phase input
    pipeline: dispatch batch N+1 before draining batch N's verdicts, with
    snapshot/replay recovery for deferred-validity failures.
    """

    # everything a dispatch, GC, regrow or compaction can move — the
    # recovery snapshot for the pipelined window (jax arrays are immutable;
    # host values are rebound, never mutated in place, by the resolve paths)
    _PIPELINE_SNAPSHOT_ATTRS = (
        "_ks", "_vs", "_bidx", "_count", "_count_ub", "_dev_count",
        "_dev_ok", "_pipelined_since_check", "_last_commit", "_base",
        "_oldest", "_cap", "_tab", "_rec_ks", "_rec_vs", "_rec_bidx",
        "_rec_dev_count", "_rec_count_ub", "_rec_cap",
        "_runs_b", "_runs_e", "_runs_ver", "_n_runs", "_run_rows_ub",
        "_run_cap",
    )

    def __init__(
        self,
        oldest_version: int = 0,
        *,
        max_key_bytes: int = keymod.DEFAULT_MAX_KEY_BYTES,
        capacity: int = 1 << 16,
        merge_impl: str | None = None,   # None: FDBTPU_MERGE_IMPL env or "scatter"
        search_impl: str | None = None,  # None: FDBTPU_SEARCH_IMPL env or "sort"
        lsm: bool | None = None,         # None: FDBTPU_LSM env ("1") or False
        recent_capacity: int = 1 << 13,  # LSM recent-level capacity
        incremental: bool | None = None,  # None: FDBTPU_INCREMENTAL env, on
        run_slots: int = 8,              # K: deferred-merge compaction threshold
        run_capacity: int = 1 << 12,     # per-run interval capacity (auto-grows)
        pallas: str | None = None,       # probe override: auto|tpu|interpret|off
    ) -> None:
        self._merge_impl = impl_from_env("merge", merge_impl)
        self._search_impl = impl_from_env("search", search_impl)
        import os

        self._lsm = (
            os.environ.get("FDBTPU_LSM", "") == "1" if lsm is None else lsm
        )
        # incremental run-append merge is the default; the per-batch
        # full-state merge stays as the opt-out fallback (FDBTPU_INCREMENTAL=0)
        self._incremental = (
            os.environ.get("FDBTPU_INCREMENTAL", "1") == "1"
            if incremental is None
            else incremental
        )
        # capability probe: Pallas-on-TPU when available, interpret on
        # request (CPU parity tests), XLA fallback otherwise
        self._probe_impl = pallas_kernel.pallas_mode(pallas) or "xla"
        self._K = run_slots
        self._run_cap = run_capacity
        self._phase_timing = os.environ.get("FDBTPU_PHASE_TIMING", "") == "1"
        self._rec_iters = _rec_search_iters()
        self._max_key_bytes = max_key_bytes
        self._W = keymod.num_words(max_key_bytes)
        self._base = oldest_version
        self._oldest = oldest_version
        self._last_commit = oldest_version
        self._cap = capacity
        self._rec_cap = recent_capacity
        # profiling counters (KernelStats): survive capacity regrows; the
        # recompile count is the number of DISTINCT static-shape combos the
        # jit cache has seen — the bucket-induced recompiles ISSUE cites
        self.stats = KernelStats(backend="device")
        self.stats.merge_impl = self._merge_impl
        self._compiled_shapes: set[tuple] = set()
        self._pipeline_init()  # staging arenas + deferred-resolve window
        self._init_state(capacity)

    def _init_state(self, capacity: int, ks=None, vs=None, count: int = 1) -> None:
        """Fresh state arrays; optionally carry over `count` live boundaries."""
        W = self._W
        nks = np.full((capacity, W), _SENT_WORD, dtype=np.uint32)
        nvs = np.zeros(capacity, dtype=np.int32)
        if ks is None:
            nks[0] = keymod.encode_keys([b""], self._max_key_bytes)[0]
        else:
            nks[:count] = np.asarray(ks)[:count]
            nvs[:count] = np.asarray(vs)[:count]
        self._cap = capacity
        self._ks = jnp.asarray(nks)
        self._vs = jnp.asarray(nvs)
        self._count = count
        self._count_ub = count
        self._dev_count = jnp.int32(count)
        if not hasattr(self, "_dev_ok"):
            # fresh construction only: a capacity regrow must NOT reset the
            # pipelined-stream validity accumulator (a pending deferred
            # failure would be silently forgotten and wrong verdicts trusted)
            self._dev_ok = jnp.asarray(True)
            self._pipelined_since_check = 0
        # diagnostics: how often the fast bucketed search failed to converge
        # (adversarial shared-prefix keys) and the full-depth replay ran
        self.search_fallbacks = getattr(self, "search_fallbacks", 0)
        self.compactions = getattr(self, "compactions", 0)
        self._bidx = jnp.asarray(host_bucket_index(nks))
        if self._lsm:
            # cached main sparse table (rebuilt only at compaction) + a
            # fresh recent level
            self._tab = build_sparse_table(self._vs, jnp.maximum, 0)
            self._init_recent(self._rec_cap)
        if self._incremental and not hasattr(self, "_runs_b"):
            # fresh construction only — a main-level regrow must not drop
            # the appended-but-uncompacted runs
            self._init_runs(self._run_cap)

    def _init_runs(self, run_cap: int) -> None:
        W = self._W
        run_cap = _bucket(run_cap)  # kernel stride math wants a power of two
        self._run_cap = run_cap
        shape = (self._K, run_cap, W)
        self._runs_b = jnp.full(shape, _SENT_WORD, dtype=jnp.uint32)
        self._runs_e = jnp.full(shape, _SENT_WORD, dtype=jnp.uint32)
        self._runs_ver = jnp.zeros(self._K, jnp.int32)
        self._n_runs = 0
        self._run_rows_ub = 0   # upper bound on live run rows (node_count)

    def _grow_runs(self, new_cap: int) -> None:
        """Sentinel-pad every run slot to new_cap (forces a stream sync:
        the np round trip waits for in-flight appends, which is exactly the
        safe point to reshape)."""
        K, W = self._K, self._W
        b = np.asarray(self._runs_b)
        e = np.asarray(self._runs_e)
        old = b.shape[1]
        nb = np.full((K, new_cap, W), _SENT_WORD, dtype=np.uint32)
        ne = np.full((K, new_cap, W), _SENT_WORD, dtype=np.uint32)
        nb[:, :old] = b
        ne[:, :old] = e
        self._run_cap = new_cap
        self._runs_b = jnp.asarray(nb)
        self._runs_e = jnp.asarray(ne)

    def _init_recent(self, rec_cap: int) -> None:
        W = self._W
        rk = np.full((rec_cap, W), _SENT_WORD, dtype=np.uint32)
        rk[0] = keymod.encode_keys([b""], self._max_key_bytes)[0]
        self._rec_cap = rec_cap
        self._rec_ks = jnp.asarray(rk)
        self._rec_vs = jnp.zeros(rec_cap, dtype=jnp.int32)
        self._rec_bidx = jnp.asarray(host_bucket_index(rk))
        self._rec_dev_count = jnp.int32(1)
        self._rec_count_ub = 1

    @property
    def oldest_version(self) -> int:
        return self._oldest

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def boundary_count(self) -> int:
        if self._count is None:
            self._count = int(self._dev_count)
        n = self._count
        if self._lsm:
            n += int(self._rec_dev_count)
        if self._incremental:
            # run rows are host-tracked as an upper bound (2*Wn per append);
            # the exact union sizes live on device and fetching them would
            # sync a pipelined stream for a status scrape
            n += self._run_rows_ub
        return n

    @property
    def node_count(self) -> int:
        """KernelStats name for the live state size (the skip-list
        node-count analog).  NOTE: forces a device scalar fetch when a
        pipelined stream has not been drained — a status scrape cost, not
        a hot-path one."""
        return self.boundary_count

    def healthcheck(self) -> bool:
        """One tiny host<->device round trip through the live state arrays:
        raises (classified by the DeviceSupervisor) if the backend is gone,
        the tunnel is wedged, or the stream is poisoned.  The fetch is a
        stream sync, so it only runs where a sync is already acceptable —
        supervisor probes and fresh-construction checks, never the hot path."""
        n = int(jnp.asarray(self._dev_count))
        return n >= 0

    def _note_shape(self, key: tuple) -> None:
        if key not in self._compiled_shapes:
            self._compiled_shapes.add(key)
            self.stats.recompiles += 1

    def _note_rows(self, rtv, wtv, R: int, Wn: int) -> None:
        """Padded-vs-real occupancy, host arrays only: counting rows of a
        device-resident array would force a sync mid-pipeline."""
        if isinstance(rtv, np.ndarray) and isinstance(wtv, np.ndarray):
            self.stats.real_rows += int((rtv >= 0).sum()) + int((wtv >= 0).sum())
            self.stats.padded_rows += R + Wn

    def _note_batch(self, t0: float, active_p, verdict_np) -> None:
        """active_p/verdict_np must be HOST arrays or None: a pipelined
        (device-resident) batch contributes timing only — counting its rows
        would force a sync, and counting txns without verdicts would deflate
        abort_rate — so txns/aborted accumulate only where verdicts are
        host-observed and the ratio stays honest."""
        if isinstance(active_p, np.ndarray) and verdict_np is not None:
            n_txn = int(active_p.sum())
            aborted = int(((verdict_np == int(Verdict.CONFLICT)) & active_p).sum())
        else:
            n_txn, aborted = 0, 0
        self.stats.note_batch(n_txn, aborted, time.perf_counter() - t0)

    def _offset(self, version: int) -> int:
        off = version - self._base
        if off >= 2**31 - 2**24:
            raise OverflowError(
                "version offset overflow: call remove_before to advance the "
                "MVCC window (reference GCs every batch, SkipList.cpp:1199)"
            )
        return max(off, 0)

    def _offset_array(self, versions: np.ndarray) -> np.ndarray:
        """Vectorized _offset twin for the bulk packer (one np pass per
        batch instead of one Python call per transaction)."""
        off = np.asarray(versions, dtype=np.int64) - self._base
        if off.size and int(off.max()) >= 2**31 - 2**24:
            raise OverflowError(
                "version offset overflow: call remove_before to advance the "
                "MVCC window (reference GCs every batch, SkipList.cpp:1199)"
            )
        return np.maximum(off, 0)

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        self._drain_all()  # settle any deferred window before sync work
        validate_batch(commit_version, txns, self._oldest)
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit_version {commit_version} not after last batch {self._last_commit}"
            )
        B = len(txns)
        if B == 0:
            self._last_commit = commit_version
            return []

        t_pack = time.perf_counter()
        rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, Bp = pack_batch(
            txns, self._oldest, self._offset, self._max_key_bytes,
            arena=self._arena, stats=self.stats,
            offset_array=self._offset_array,
        )
        self.stats.pack_s += time.perf_counter() - t_pack
        codes = self.resolve_arrays(
            commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p
        )
        return [Verdict(int(c)) for c in codes[:B]]

    def resolve_arrays(
        self, commit_version: int, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
        sync: bool = True,
    ):
        """Packed fast path: pre-encoded/padded arrays (see pack_batch for the
        layout; snap_p already offset against this set's base).  This is the
        form the resolver role feeds the device — batches arrive packed from
        the proxy, the TxInfo path above is the convenience wrapper.

        sync=True (default): returns np verdicts; handles search fallback and
        capacity regrow inline (one host<->device round trip per batch).

        sync=False: PIPELINED mode — dispatches the kernel and returns the
        verdicts as a device array WITHOUT waiting; the search-convergence
        and capacity checks are queued and must be drained with
        `check_pipelined()` before the verdicts are trusted.  Batch N+1's
        check only needs batch N's device-resident state, so a stream of
        resolves overlaps compute with the host link — the double-buffered
        device queue SURVEY §7 calls load-bearing for hiding transfer
        latency.  If a deferred check fails, check_pipelined raises and the
        caller must replay through the sync path (kernel is pure, so the
        host-side TxInfo stream is the source of truth)."""
        if sync and self._inflight:
            # mixed use: a deferred window is open — settle it first so the
            # sync result (and any regrow/fallback replay) sees final state
            self._drain_all()
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit_version {commit_version} not after last batch {self._last_commit}"
            )
        Bp, R, Wn = snap_p.shape[0], rbv.shape[0], wbv.shape[0]
        commit_off = np.int32(self._offset(commit_version))
        t0 = time.perf_counter()

        if self._incremental:
            return self._resolve_arrays_inc(
                commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                sync, Bp, R, Wn, commit_off,
            )

        if self._lsm:
            return self._resolve_arrays_lsm(
                commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                sync, Bp, R, Wn, commit_off,
            )

        if not sync:
            # capacity margin: a batch adds at most 2*Wn boundaries; if the
            # host-tracked upper bound could overflow, drain the pipeline
            # (one fetch) to learn the exact count — and if genuinely near
            # capacity, fall through to the sync path, which regrows
            if self._count_ub + 2 * Wn > self._cap:
                self.check_pipelined()
                if self._count_ub + 2 * Wn > self._cap:
                    return np.asarray(
                        self.resolve_arrays(
                            commit_version, rbv, rev, rtv, wbv, wev, wtv,
                            snap_p, active_p, sync=True,
                        )
                    )
            self._note_shape(
                ("flat", self._cap, Bp, R, Wn, FAST_SEARCH_ITERS,
                 self._merge_impl, self._search_impl)
            )
            verdict, new_ks, new_vs, new_count, new_bidx, _conv, ok = _resolve_kernel(
                self._ks, self._vs, self._bidx, self._dev_count,
                rbv, rev, rtv, wbv, wev, wtv,
                snap_p, active_p, commit_off, self._dev_ok,
                cap=self._cap, n_txn=Bp, n_read=R, n_write=Wn,
                search_iters=FAST_SEARCH_ITERS,
                merge_impl=self._merge_impl,
                search_impl=self._search_impl,
            )
            self._ks, self._vs, self._bidx = new_ks, new_vs, new_bidx
            self._dev_count = new_count
            self._dev_ok = ok
            self._count = None  # unknown until drained
            self._count_ub += 2 * Wn
            self._pipelined_since_check += 1
            self._last_commit = commit_version
            self.stats.full_merges += 1
            self._note_rows(rtv, wtv, R, Wn)
            self._note_batch(t0, active_p, None)  # dispatch time only
            return verdict

        while True:
            pre_ks, pre_vs, pre_dev_count = self._ks, self._vs, self._dev_count
            iters = min(FAST_SEARCH_ITERS, _levels(self._cap) + 1)
            while True:
                # ok_in as a device array so this shares ONE compiled
                # executable with the pipelined path (a Python True traces
                # as a weak-typed scalar => a second compile of the kernel)
                self._note_shape(
                    ("flat", self._cap, Bp, R, Wn, iters,
                     self._merge_impl, self._search_impl)
                )
                verdict, new_ks, new_vs, new_count, new_bidx, conv, _ok = _resolve_kernel(
                    self._ks, self._vs, self._bidx, self._dev_count,
                    rbv, rev, rtv, wbv, wev, wtv,
                    snap_p, active_p, commit_off, jnp.asarray(True),
                    cap=self._cap, n_txn=Bp, n_read=R, n_write=Wn,
                    search_iters=iters,
                    merge_impl=self._merge_impl,
                    search_impl=self._search_impl,
                )
                if bool(conv):
                    break
                # a word0-prefix bucket was deeper than 2**iters (adversarial
                # shared-prefix keys): replay at full search depth — the
                # kernel is pure, so the replay is exact
                self.search_fallbacks += 1
                self.stats.search_fallbacks += 1
                testcov("kernel.search_fallback.flat")
                iters = _levels(self._cap) + 1
            new_count_i = int(new_count)
            if new_count_i <= self._cap:
                self._ks, self._vs, self._count = new_ks, new_vs, new_count_i
                self._count_ub = new_count_i
                self._dev_count = new_count
                self._bidx = new_bidx
                self._last_commit = commit_version
                self.stats.full_merges += 1
                break
            # capacity overflow: the merge dropped boundaries — regrow from
            # the pre-batch state (still valid: the kernel does not donate
            # its inputs) and replay.
            self._init_state(
                max(self._cap * 2, _bucket(new_count_i)),
                np.asarray(pre_ks), np.asarray(pre_vs), int(pre_dev_count),
            )
        v_np = np.asarray(verdict)
        self._note_rows(rtv, wtv, R, Wn)
        self._note_batch(
            t0, active_p, v_np if isinstance(active_p, np.ndarray) else None
        )
        return v_np

    # -- LSM paths -----------------------------------------------------------
    def _resolve_arrays_lsm(
        self, commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
        sync, Bp, R, Wn, commit_off,
    ):
        t0 = time.perf_counter()
        # a single batch bigger than the recent level: grow recent first
        if 2 * Wn + 1 > self._rec_cap:
            self._grow_recent(_bucket(4 * Wn + 2))
        # proactive compaction: recent must be able to absorb this batch
        # (count upper bound is exact in sync mode, conservative pipelined)
        if self._rec_count_ub + 2 * Wn > self._rec_cap:
            self._compact()

        if not sync:
            self._note_shape(
                ("lsm", self._cap, self._rec_cap, Bp, R, Wn, FAST_SEARCH_ITERS,
                 min(self._rec_iters, _levels(self._rec_cap) + 1),
                 self._search_impl, self._merge_impl)
            )
            verdict, nrk, nrv, nrb, nrc, _conv, ok = _resolve_lsm_kernel(
                self._ks, self._vs, self._tab, self._bidx, self._dev_count,
                self._rec_ks, self._rec_vs, self._rec_bidx, self._rec_dev_count,
                rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, commit_off,
                self._dev_ok,
                cap=self._cap, rec_cap=self._rec_cap,
                n_txn=Bp, n_read=R, n_write=Wn,
                rec_iters=min(self._rec_iters, _levels(self._rec_cap) + 1),
                search_impl=self._search_impl, merge_impl=self._merge_impl,
            )
            self._rec_ks, self._rec_vs, self._rec_bidx = nrk, nrv, nrb
            self._rec_dev_count = nrc
            self._dev_ok = ok
            self._rec_count_ub += 2 * Wn
            self._pipelined_since_check += 1
            self._last_commit = commit_version
            self.stats.full_merges += 1
            self._note_rows(rtv, wtv, R, Wn)
            self._note_batch(t0, active_p, None)  # dispatch time only
            return verdict

        iters = min(FAST_SEARCH_ITERS, _levels(self._cap) + 1)
        rec_iters = min(self._rec_iters, _levels(self._rec_cap) + 1)
        while True:
            self._note_shape(
                ("lsm", self._cap, self._rec_cap, Bp, R, Wn, iters, rec_iters,
                 self._search_impl, self._merge_impl)
            )
            verdict, nrk, nrv, nrb, nrc, conv, _ok = _resolve_lsm_kernel(
                self._ks, self._vs, self._tab, self._bidx, self._dev_count,
                self._rec_ks, self._rec_vs, self._rec_bidx, self._rec_dev_count,
                rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, commit_off,
                jnp.asarray(True),
                cap=self._cap, rec_cap=self._rec_cap,
                n_txn=Bp, n_read=R, n_write=Wn,
                search_iters=iters, rec_iters=rec_iters,
                search_impl=self._search_impl, merge_impl=self._merge_impl,
            )
            if bool(conv):
                break
            self.search_fallbacks += 1
            self.stats.search_fallbacks += 1
            testcov("kernel.search_fallback.lsm")
            iters = _levels(self._cap) + 1
            rec_iters = _levels(self._rec_cap) + 1
        nrc_i = int(nrc)
        if nrc_i > self._rec_cap:
            # recent overflowed despite the proactive check (coalescing
            # estimate beaten): compact (pre-batch recent is intact — the
            # kernel does not donate) and replay this batch
            self._compact()
            return self._resolve_arrays_lsm(
                commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p,
                active_p, sync, Bp, R, Wn, commit_off,
            )
        self._rec_ks, self._rec_vs, self._rec_bidx = nrk, nrv, nrb
        self._rec_dev_count = jnp.int32(nrc_i)
        self._rec_count_ub = nrc_i
        self._last_commit = commit_version
        self.stats.full_merges += 1
        v_np = np.asarray(verdict)
        self._note_rows(rtv, wtv, R, Wn)
        self._note_batch(
            t0, active_p, v_np if isinstance(active_p, np.ndarray) else None
        )
        return v_np

    # -- incremental (run-append) path ---------------------------------------
    def _resolve_arrays_inc(
        self, commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
        sync, Bp, R, Wn, commit_off,
    ):
        """Incremental resolve: main level is read-only per batch, committed
        writes append as run `self._n_runs`, compaction (the deferred k-way
        merge) fires host-side when the K slots fill.  Works for both the
        flat layout (history table rebuilt per batch from vs) and the LSM
        layout (cached table).  All run bookkeeping is host-deterministic:
        appends cannot overflow (run_cap >= 2*Wn by construction), so the
        pipelined path defers only search convergence."""
        t0 = time.perf_counter()
        if 2 * Wn > self._run_cap:
            self._grow_runs(_bucket(2 * Wn))
        if self._n_runs >= self._K:
            self._compact_runs()
        slot = jnp.int32(self._n_runs)
        kernel = _resolve_inc_lsm_kernel if self._lsm else _resolve_inc_kernel
        hist_base = self._tab if self._lsm else self._vs
        statics = dict(
            cap=self._cap, run_cap=self._run_cap, n_txn=Bp, n_read=R,
            n_write=Wn, search_impl=self._search_impl,
            probe_impl=self._probe_impl, merge_impl=self._merge_impl,
        )

        def dispatch(ok_in, iters):
            self._note_shape(
                ("inc", self._lsm, self._cap, self._run_cap, self._K,
                 Bp, R, Wn, iters, self._search_impl, self._probe_impl,
                 self._merge_impl)
            )
            return kernel(
                self._ks, hist_base, self._bidx, self._dev_count,
                self._runs_b, self._runs_e, self._runs_ver, slot,
                rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, commit_off,
                ok_in, search_iters=iters, **statics,
            )

        if not sync:
            verdict, nb, ne, nv, _conv, ok = dispatch(
                self._dev_ok, min(FAST_SEARCH_ITERS, _levels(self._cap) + 1)
            )
            self._runs_b, self._runs_e, self._runs_ver = nb, ne, nv
            self._dev_ok = ok
            self._n_runs += 1
            self._run_rows_ub += 2 * Wn
            self._pipelined_since_check += 1
            self._last_commit = commit_version
            self.stats.runs_appended += 1
            self._note_rows(rtv, wtv, R, Wn)
            self._note_batch(t0, active_p, None)  # dispatch time only
            return verdict

        if self._phase_timing:
            verdict, nb, ne, nv = self._resolve_inc_timed(
                rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, commit_off,
                slot, hist_base, statics,
            )
        else:
            iters = min(FAST_SEARCH_ITERS, _levels(self._cap) + 1)
            while True:
                verdict, nb, ne, nv, conv, _ok = dispatch(
                    jnp.asarray(True), iters
                )
                if bool(conv):
                    break
                self.search_fallbacks += 1
                self.stats.search_fallbacks += 1
                testcov("kernel.search_fallback.inc")
                iters = _levels(self._cap) + 1
        self._runs_b, self._runs_e, self._runs_ver = nb, ne, nv
        self._n_runs += 1
        self._run_rows_ub += 2 * Wn
        self._last_commit = commit_version
        self.stats.runs_appended += 1
        v_np = np.asarray(verdict)
        self._note_rows(rtv, wtv, R, Wn)
        self._note_batch(
            t0, active_p, v_np if isinstance(active_p, np.ndarray) else None
        )
        return v_np

    def _resolve_inc_timed(
        self, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, commit_off,
        slot, hist_base, statics,
    ):
        """Split-phase sync resolve (FDBTPU_PHASE_TIMING=1): each phase is
        its own dispatch + completion barrier so sort/scan/merge wall times
        land in KernelStats individually.  Same math as the fused kernel —
        the phases are the same traced functions."""
        Bp = statics["n_txn"]
        iters = min(FAST_SEARCH_ITERS, _levels(self._cap) + 1)
        while True:
            t = time.perf_counter()
            g_lo, g_hi, conv = _inc_search_kernel(
                self._ks, self._bidx, self._dev_count, rbv, rev, rtv,
                search_iters=iters, search_impl=self._search_impl,
            )
            jax.block_until_ready(g_lo)
            self.stats.sort_s += time.perf_counter() - t
            if bool(conv):
                break
            self.search_fallbacks += 1
            self.stats.search_fallbacks += 1
            testcov("kernel.search_fallback.inc_timed")
            iters = _levels(self._cap) + 1
        t = time.perf_counter()
        verdict, w_ins = _inc_check_kernel(
            hist_base, g_lo, g_hi, rbv, rev, rtv, wbv, wev, wtv,
            snap_p, active_p, self._runs_b, self._runs_e, self._runs_ver,
            n_txn=Bp, probe_impl=self._probe_impl, from_table=self._lsm,
        )
        jax.block_until_ready(verdict)
        self.stats.scan_s += time.perf_counter() - t
        t = time.perf_counter()
        nb, ne, nv = _inc_append_kernel(
            self._runs_b, self._runs_e, self._runs_ver, slot,
            wbv, wev, w_ins, commit_off, run_cap=self._run_cap,
            merge_impl=self._merge_impl,
        )
        jax.block_until_ready(nv)
        self.stats.append_s += time.perf_counter() - t
        return verdict, nb, ne, nv

    def _compact_runs(self) -> None:
        """The deferred k-way merge: fold every appended run (each a step
        function at one commit version) into the main level via the
        existing max-compose (compact_lsm), regrowing main when a fold's
        union outgrows it.  The ONLY full-state sorts on the incremental
        path happen here — once per K batches, not per batch."""
        if self._n_runs == 0:
            return
        t0 = time.perf_counter()
        before = self._count_ub + self._run_rows_ub
        nc_i = self._count_ub
        for s in range(self._n_runs):
            rows, vals = _run_step_kernel(
                self._runs_b[s], self._runs_e[s], self._runs_ver[s],
                impl=self._probe_impl,
            )
            while True:
                nk, nv, nc, nb, nt = _compact_kernel(
                    self._ks, self._vs, rows, vals, cap=self._cap,
                    merge_impl=self._merge_impl, lowering=self._probe_impl,
                )
                nc_i = int(nc)
                if nc_i <= self._cap:
                    break
                self._grow_main(max(self._cap * 2, _bucket(nc_i)))
            self._ks, self._vs, self._bidx = nk, nv, nb
            if self._lsm:
                self._tab = nt
        self._count = nc_i
        self._count_ub = nc_i
        self._dev_count = jnp.int32(nc_i)
        self._init_runs(self._run_cap)
        self.compactions += 1
        self.stats.compactions += 1
        self.stats.rows_reclaimed += max(0, before - nc_i)
        dt = time.perf_counter() - t0
        self.stats.compact_s += dt
        self.stats.merge_s += dt
        self.stats.fold_wall_s[self._merge_impl] = (
            self.stats.fold_wall_s.get(self._merge_impl, 0.0) + dt
        )
        testcov("kernel.run_compaction")

    def _compact(self) -> None:
        """Fold recent into main; regrow main if the union does not fit."""
        t0 = time.perf_counter()
        before = self._count_ub + self._rec_count_ub
        while True:
            nk, nv, nc, nb, nt = _compact_kernel(
                self._ks, self._vs, self._rec_ks, self._rec_vs, cap=self._cap,
                merge_impl=self._merge_impl, lowering=self._probe_impl,
            )
            nc_i = int(nc)
            if nc_i <= self._cap:
                break
            self._grow_main(max(self._cap * 2, _bucket(nc_i)))
        self._ks, self._vs, self._bidx, self._tab = nk, nv, nb, nt
        self._count = nc_i
        self._count_ub = nc_i
        self._dev_count = jnp.int32(nc_i)
        self._init_recent(self._rec_cap)
        self.compactions += 1
        self.stats.compactions += 1
        self.stats.rows_reclaimed += max(0, before - nc_i)
        dt = time.perf_counter() - t0
        self.stats.merge_s += dt
        self.stats.fold_wall_s[self._merge_impl] = (
            self.stats.fold_wall_s.get(self._merge_impl, 0.0) + dt
        )
        testcov("kernel.lsm_compaction")

    def _grow_main(self, new_cap: int) -> None:
        ks = np.asarray(self._ks)
        vs = np.asarray(self._vs)
        W = self._W
        nks = np.full((new_cap, W), _SENT_WORD, dtype=np.uint32)
        nks[: ks.shape[0]] = ks
        nvs = np.zeros(new_cap, dtype=np.int32)
        nvs[: vs.shape[0]] = vs
        self._cap = new_cap
        self._ks = jnp.asarray(nks)
        self._vs = jnp.asarray(nvs)
        self._bidx = jnp.asarray(host_bucket_index(nks))
        self._tab = build_sparse_table(self._vs, jnp.maximum, 0)

    def _grow_recent(self, new_rec_cap: int) -> None:
        rk = np.asarray(self._rec_ks)
        rv = np.asarray(self._rec_vs)
        W = self._W
        nks = np.full((new_rec_cap, W), _SENT_WORD, dtype=np.uint32)
        nks[: rk.shape[0]] = rk
        nvs = np.zeros(new_rec_cap, dtype=np.int32)
        nvs[: rv.shape[0]] = rv
        count, ub = self._rec_dev_count, self._rec_count_ub
        self._rec_cap = new_rec_cap
        self._rec_ks = jnp.asarray(nks)
        self._rec_vs = jnp.asarray(nvs)
        self._rec_bidx = jnp.asarray(host_bucket_index(nks))
        self._rec_dev_count = count
        self._rec_count_ub = ub

    def check_pipelined(self) -> None:
        """Drain the deferred validity of sync=False resolves: ONE device
        flag (folded across the stream by the kernel itself) plus the live
        count — two scalar fetches total, regardless of stream length.
        Raises if any batch's search needed the full-depth fallback or the
        state overflowed capacity; the stream must then be replayed through
        sync=True resolves (the kernel is pure, so the host-side batch
        stream is the source of truth)."""
        if self._pipelined_since_check == 0:
            return
        n = self._pipelined_since_check
        self._pipelined_since_check = 0
        if not bool(self._dev_ok):
            raise RuntimeError(
                f"a pipelined batch among the last {n} failed its deferred"
                " search-convergence/capacity check; replay through sync=True"
            )
        if self._lsm:
            self._rec_count_ub = int(self._rec_dev_count)
        else:
            self._count = int(self._dev_count)
            self._count_ub = self._count

    def remove_before(self, version: int) -> None:
        if version <= self._oldest:
            return
        self._oldest = version
        off = version - self._base
        if off > 0:
            t0 = time.perf_counter()
            if self._inflight:
                # a deferred window is open: the recovery snapshot may alias
                # these buffers, so clamp WITHOUT donation (eager ops build
                # fresh arrays; GC is rare relative to resolves)
                o = jnp.int32(off)
                self._vs = jnp.maximum(self._vs - o, 0)
                if self._lsm:
                    self._tab = jnp.maximum(self._tab - o, 0)
                    self._rec_vs = jnp.maximum(self._rec_vs - o, 0)
            elif self._lsm:
                self._vs, self._tab, self._rec_vs = _gc_lsm_kernel(
                    self._vs, self._tab, self._rec_vs, np.int32(off)
                )
            else:
                self._ks, self._vs = _gc_kernel(self._ks, self._vs, np.int32(off))
            if self._incremental:
                # a run whose version falls out of the MVCC window clamps
                # to 0 and can never conflict again (snapshots are >= 0) —
                # the same dead-gap semantics as the step-function clamp
                self._runs_ver = jnp.maximum(
                    self._runs_ver - jnp.int32(off), 0
                )
            self._base = version
            self.stats.gc_calls += 1
            self.stats.merge_s += time.perf_counter() - t0
            self._note_pipeline_gc(version)
