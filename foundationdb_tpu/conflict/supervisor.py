"""DeviceSupervisor — the hardware conflict backend behind a circuit breaker.

The plugin boundary's promise (PAPER.md: the TPU kernel is an *optimization*
"so the commit path and a CPU reference implementation remain intact") is
only real if a sick device cannot take the resolver down with it.  This
module makes that promise enforceable: every device interaction — compile
probe, dispatch, deferred readback, GC, state replay — runs under a bounded
watchdog with knob-controlled retry + exponential backoff (the
DEFAULT_BACKOFF family, runtime/knobs.py DEVICE_*), and after
DEVICE_RETRY_LIMIT consecutive failures a circuit breaker trips and the
resolver **degrades gracefully to the CPU reference backend**:

  * the supervisor keeps a committed-write-window record — (commit_version,
    committed write ranges) for every batch inside the MVCC window, the
    same snapshot/replay discipline conflict/pipeline.py uses for
    deferred-failure recovery, lifted ABOVE the device so it survives full
    device loss (including loss mid-pipeline with a deferred window open);
  * on degrade it reconstructs an equivalent ``oracle``/``native``
    ConflictSet by replaying that record (write-only batches commute with
    GC, so the rebuild is exact), replays any open deferred window through
    it in dispatch order with the recorded GC interleaving, and keeps
    serving version-ordered verdicts — zero transactions aborted in error;
  * while degraded it re-probes the device every DEVICE_REPROBE_INTERVAL
    (virtual clock under simulation via ``bind_clock``, wall clock on the
    real network) and re-promotes by rebuilding device state from the
    record; the handoff is trusted only after an abort-set parity check on
    the first promoted batch (device and CPU both resolve it; any mismatch
    demotes again).

Failure classes (``classify_failure``): hang (watchdog), lost (runtime /
tunnel death), compile_fail, readback_corrupt (validate_verdicts or parity
mismatch), no_device.  Each is injectable under simulation via the buggify
sites ``device.dispatch_hang``, ``device.lost``, ``device.compile_fail``,
``device.readback_corrupt`` so the chaos sweep can kill the device at
arbitrary points in the split-phase pipeline.  Health feeds
``rpc/failmon.py`` (``note_device``) and ``control/status.py``
(state / trip counts / time degraded) — docs/OPERATIONS.md has the
degraded-mode runbook.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence

from .api import (
    CompletedResolve,
    ConflictSet,
    ResolveHandle,
    TxInfo,
    Verdict,
    VerdictValidationError,
    validate_verdicts,
)
from ..runtime.buggify import buggify
from ..runtime.coverage import testcov


class DeviceError(RuntimeError):
    """Base class of classified device-backend failures."""

    failure_class = "error"


class DeviceHang(DeviceError):
    failure_class = "hang"


class DeviceLost(DeviceError):
    failure_class = "lost"


class DeviceCompileFail(DeviceError):
    failure_class = "compile_fail"


class DeviceReadbackCorrupt(DeviceError):
    failure_class = "readback_corrupt"


# substrings that classify an unstructured backend error (JAX/PJRT raise
# plain RuntimeError/XlaRuntimeError; the tunnel's death shows up as
# UNAVAILABLE / connection text, a missing accelerator as init failures)
_CLASS_PATTERNS = (
    ("no_device", (
        "no visible device", "unable to initialize backend",
        "failed to initialize", "no devices", "device not found",
        "backend 'tpu' requested",
    )),
    ("compile_fail", ("compil", "lowering", "mosaic", "unsupported hlo")),
    ("lost", (
        "unavailable", "connection", "socket closed", "deadline exceeded",
        "device lost", "reset by peer", "data loss", "internal:",
    )),
)


def classify_failure(err) -> str:
    """Map an exception (or error text) to a failure class: one of
    hang | lost | compile_fail | readback_corrupt | no_device | error.
    Shared by the supervisor and the bench device probe so operators see
    ONE vocabulary in probe.log, status, and traces."""
    if isinstance(err, DeviceError):
        return err.failure_class
    if isinstance(err, TimeoutError):
        return "hang"
    text = str(err).lower()
    for cls, pats in _CLASS_PATTERNS:
        if any(p in text for p in pats):
            return cls
    return "error"


class Watchdog:
    """Bounded execution of a (possibly blocking) device call.

    wall=True runs the call on a persistent single worker thread and raises
    DeviceHang past ``timeout_s`` — the real-network mode where a hung PJRT
    dispatch must not wedge the resolver (the wedged daemon worker is
    abandoned and replaced; the caller quarantines the device).
    wall=False (the simulation default) calls inline: deterministic sims
    cannot thread, so hangs there are *injected* as DeviceHang by the
    ``device.dispatch_hang`` buggify site instead — virtual-clock
    supervision with the same downstream handling."""

    def __init__(self, timeout_s: float | None, wall: bool = False) -> None:
        self.timeout_s = timeout_s
        self.wall = wall
        self._worker = None
        self._q = None

    @staticmethod
    def _serve(q) -> None:
        while True:
            item = q.get()
            if item is None:
                return
            fn, box, done = item
            try:
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised by run()
                box["error"] = e
            finally:
                done.set()

    def run(self, fn: Callable):
        if not self.wall or not self.timeout_s:
            return fn()
        # ONE persistent DAEMON worker: the hot path pays a queue hop per
        # call, not a thread spawn.  Daemon matters — a wedged worker must
        # never be joined again, not by us and not by the interpreter
        # (ThreadPoolExecutor workers are non-daemon and the
        # concurrent.futures atexit hook joins them, which would turn one
        # tripped watchdog into a process that can never exit).
        import queue
        import threading

        if self._worker is None or not self._worker.is_alive():
            self._q = queue.SimpleQueue()
            self._worker = threading.Thread(
                target=self._serve, args=(self._q,), daemon=True
            )
            self._worker.start()
        box: dict = {}
        done = threading.Event()
        self._q.put((fn, box, done))
        if not done.wait(self.timeout_s):
            # abandon the wedged worker: its queue gets no more work, so if
            # it ever unwedges it parks on an empty queue until process exit
            self._worker = None
            self._q = None
            raise DeviceHang(
                f"device call exceeded watchdog {self.timeout_s:.0f}s"
            )
        if "error" in box:
            raise box["error"]
        return box["value"]

    def close(self) -> None:
        if self._q is not None:
            self._q.put(None)  # let an idle worker exit promptly
        self._worker = None
        self._q = None


class _WinEntry:
    """One dispatched batch of the supervised deferred window: enough to
    replay it through the CPU fallback if the device dies before (or
    while) its verdicts are read.  ``gc_after`` holds the remove_before
    floors issued while this entry was the newest dispatch — i.e. after
    this batch resolved on the device and before its successor did."""

    __slots__ = ("version", "txns", "inner", "gc_after", "result")

    def __init__(self, version: int, txns, inner: ResolveHandle) -> None:
        self.version = version
        self.txns = txns            # () once recorded
        self.inner = inner
        self.gc_after: list[int] = []
        self.result: list[Verdict] | None = None


class SupervisedHandle(ResolveHandle):
    """ResolveHandle whose wait() routes through the supervisor so a device
    failure during readback degrades and recovers the whole window."""

    __slots__ = ("_sup", "_entry")

    def __init__(self, sup: "DeviceSupervisor", entry: _WinEntry) -> None:
        self._sup = sup
        self._entry = entry

    def wait(self) -> list[Verdict]:
        return self._sup._wait_entry(self._entry)


class DeviceSupervisor(ConflictSet):
    """ConflictSet that supervises a device-backed implementation and
    degrades to a CPU reference backend rather than failing.

    ``device_factory(oldest_version)`` builds the supervised backend
    (DeviceConflictSet / ShardedDeviceConflictSet / a plugin);
    ``fallback_factory(oldest_version)`` builds the CPU reference
    (OracleConflictSet by default; conflict.native.NativeConflictSet where
    the C++ skip list is built).  ``knobs`` supplies the DEVICE_* family;
    ``clock`` paces backoff/re-probe scheduling (time.monotonic by default —
    the Resolver rebinds it to the sim loop's virtual clock via
    ``bind_clock``, so supervision is deterministic under simulation)."""

    def __init__(
        self,
        device_factory: Callable[[int], ConflictSet],
        *,
        fallback_factory: Callable[[int], ConflictSet] | None = None,
        oldest_version: int = 0,
        knobs=None,
        clock: Callable[[], float] | None = None,
        wall_watchdog: bool = False,
        name: str = "device",
    ) -> None:
        import os

        from ..runtime.knobs import CoreKnobs
        from .oracle import OracleConflictSet

        self.name = name
        self._device_factory = device_factory
        self._fallback_factory = fallback_factory or (
            lambda oldest=0: OracleConflictSet(oldest)
        )
        k = knobs or CoreKnobs()
        self.watchdog_s = float(k.DEVICE_WATCHDOG_S)
        self.retry_limit = int(k.DEVICE_RETRY_LIMIT)
        self.backoff0 = float(k.DEVICE_RETRY_BACKOFF)
        self.max_backoff = float(k.DEVICE_MAX_BACKOFF)
        self.reprobe_interval = float(k.DEVICE_REPROBE_INTERVAL)
        self._clock = clock or time.monotonic  # flowlint: ok wall-clock (real-network default; the resolver binds the sim clock under sim)
        self._watchdog = Watchdog(self.watchdog_s, wall=wall_watchdog)

        # committed-write-window record: [(version, ((b, e), ...)), ...]
        # ascending; the CPU/device rebuild source of truth.  `_floor` is
        # the reported TooOld floor (advances on every remove_before);
        # `_record_floor` is the floor the RECORD is pruned to — it lags
        # while a deferred window is open so a mid-window rebuild can
        # replay each open batch at its dispatch-time floor.
        self._record: list[tuple[int, tuple[tuple[bytes, bytes], ...]]] = []
        self._floor = oldest_version
        self._record_floor = oldest_version
        self._window: list[_WinEntry] = []

        # health / breaker state
        self._state = "healthy"
        self._fails = 0          # consecutive failures since last success
        self._trips = 0          # breaker trips (healthy -> degraded)
        self._promotions = 0
        self._probes = 0
        self._last_failure: str | None = None
        self._degraded_since: float | None = None
        self._time_degraded = 0.0
        self._suspect = False    # device stale/quarantined, breaker not tripped
        self._parity_pending = False
        self._forced = False
        self._backoff = self.backoff0
        self._next_attempt = self._clock()  # earliest next device (re)build
        self._failmon = None
        self._failmon_name = name

        self._cpu: ConflictSet | None = None
        self._dev: ConflictSet | None = None
        # device construction is LAZY: the first resolve probes and promotes
        # (parity-checked), AFTER the owning role has had the chance to
        # bind_clock()/enable_wall_watchdog() — a construction-time probe
        # would run the very first (and historically hang-prone) PJRT init
        # unbounded, before any watchdog could be armed
        if os.environ.get("FDBTPU_FORCE_DEGRADE", "") == "1":
            # operator force-degrade knob (docs/OPERATIONS.md): start on the
            # CPU reference and stay there until force_promote()
            self.force_degrade()

    # -- wiring ---------------------------------------------------------------
    def bind_clock(self, clock: Callable[[], float]) -> None:
        """Re-anchor backoff/re-probe pacing to a different clock (the sim
        loop's virtual now()); called by the Resolver at construction."""
        self._clock = clock
        self._next_attempt = clock()
        if self._degraded_since is not None:
            self._degraded_since = clock()

    def bind_failmon(self, failmon, name: str | None = None) -> None:
        """Feed device health transitions into the cluster failure monitor."""
        self._failmon = failmon
        if name is not None:
            self._failmon_name = name
        self._feed_failmon()

    def enable_wall_watchdog(self) -> None:
        """Switch the watchdog to wall-clock worker-thread enforcement —
        called by the Resolver when it finds itself on the REAL network
        (threads are forbidden under deterministic simulation, where hangs
        are injected virtually instead)."""
        if not self._watchdog.wall:
            self._watchdog.close()
            self._watchdog = Watchdog(self.watchdog_s, wall=True)

    # -- ConflictSet surface --------------------------------------------------
    @property
    def oldest_version(self) -> int:
        return self._floor

    @property
    def node_count(self) -> int:
        be = self._active_backend()
        try:
            # watchdog-bounded: node_count forces a device scalar fetch,
            # and a status scrape must never hang on a wedged tunnel
            return (
                int(self._watchdog.run(lambda: be.node_count))
                if be is not None else 0
            )
        except Exception:  # noqa: BLE001 — a sick device must not wedge status
            return 0

    def kernel_stats(self) -> dict:
        be = self._active_backend()
        if be is None:
            snap = super().kernel_stats()
        else:
            try:
                snap = self._watchdog.run(be.kernel_stats)
            except Exception:  # noqa: BLE001 — status scrape on a dying device
                snap = super().kernel_stats()
        snap["supervisor"] = self.health()
        return snap

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        if self._window and self._device_serving():
            # mixed use: a deferred window is open — settle EVERY entry, in
            # order, before sync work so the record stays version-ordered
            # (the device mixin drains its own stream the same way)
            for e in list(self._window):
                self._wait_entry(e)
        self._settle_window()
        self._maybe_attempt_device()
        if self._device_serving():
            if self._parity_pending:
                return self._resolve_parity(commit_version, txns)
            try:
                verdicts = self._guard(
                    "dispatch",
                    lambda: self._dev.resolve_batch(commit_version, txns),
                )
                verdicts = self._inject_corrupt(verdicts)
                validate_verdicts(verdicts, len(txns))
            except Exception as e:  # noqa: BLE001 — classified or re-raised
                self._classify_or_reraise("dispatch", e)
                self._recover_window()
            else:
                self._note_success()
                self._record_batch(commit_version, txns, verdicts)
                return verdicts
        return self._cpu_resolve(commit_version, txns)

    def resolve_deferred(self, commit_version: int, txns: Sequence[TxInfo]) -> ResolveHandle:
        self._maybe_attempt_device()
        # parity batches resolve synchronously (both backends must see them)
        if not self._device_serving() or self._parity_pending:
            return CompletedResolve(self.resolve_batch(commit_version, txns))
        try:
            inner = self._guard(
                "dispatch",
                lambda: self._dev.resolve_deferred(commit_version, txns),
            )
        except Exception as e:  # noqa: BLE001 — device died at dispatch
            self._classify_or_reraise("dispatch", e)
            self._recover_window()
            return CompletedResolve(self._cpu_resolve(commit_version, txns))
        entry = _WinEntry(commit_version, list(txns), inner)
        self._window.append(entry)
        if isinstance(inner, CompletedResolve):
            # the backend fell through to a synchronous resolve internally
            # (empty batch / capacity margin): verdicts are already final —
            # complete through this entry now so the record stays ordered
            self._wait_entry(entry)
        return SupervisedHandle(self, entry)

    def remove_before(self, version: int) -> None:
        if version <= self._floor:
            return
        self._floor = version
        if self._window:
            # defer record pruning: a mid-window rebuild must replay each
            # open batch at its dispatch-time floor (same discipline as
            # pipeline.py _note_pipeline_gc)
            self._window[-1].gc_after.append(version)
        else:
            self._apply_record_floor(version)
        if self._cpu is not None:
            self._cpu.remove_before(version)
        if self._device_serving():
            try:
                self._guard("gc", lambda: self._dev.remove_before(version))
            except Exception as e:  # noqa: BLE001 — classified device failure
                self._classify_or_reraise("gc", e)
                self._recover_window()

    def healthcheck(self) -> bool:
        be = self._active_backend()
        return self._watchdog.run(be.healthcheck) if be is not None else True

    def close(self) -> None:
        self._watchdog.close()
        for be in (self._dev, self._cpu):
            if be is not None:
                try:
                    be.close()
                except Exception:  # noqa: BLE001 — teardown must not raise
                    pass
        self._dev = self._cpu = None

    # -- health surface -------------------------------------------------------
    def health(self) -> dict:
        t_deg = self._time_degraded
        if self._degraded_since is not None:
            t_deg += self._clock() - self._degraded_since
        serving_device = self._device_serving() and not self._parity_pending
        return {
            "state": self._state,
            # while a parity check is pending the CPU's verdicts are what
            # gets served, so that is what the field reports
            "serving": "device" if serving_device else "cpu",
            "trips": self._trips,
            "consecutive_failures": self._fails,
            "last_failure": self._last_failure,
            "time_degraded_s": t_deg,
            "probes": self._probes,
            "promotions": self._promotions,
            "recorded_batches": len(self._record),
        }

    def force_degrade(self) -> None:
        """Operator knob: drop to the CPU reference now and stop re-probing
        (until force_promote()).  Safe at any point — an open deferred
        window is recovered exactly like an injected device loss."""
        self._ensure_cpu()
        if self._window:
            self._recover_window()
        self._drop_device()
        if self._state != "degraded":
            self._state = "degraded"
            self._trips += 1
            self._degraded_since = self._clock()
            self._feed_failmon()
        self._forced = True
        testcov("device.force_degrade")

    def force_promote(self) -> None:
        """Operator knob: clear a force_degrade and re-probe immediately
        (the promotion still passes through the parity check)."""
        self._forced = False
        self._next_attempt = self._clock()
        self._maybe_attempt_device()

    # -- internals ------------------------------------------------------------
    def _device_serving(self) -> bool:
        return self._dev is not None and not self._suspect

    def _active_backend(self) -> ConflictSet | None:
        return self._dev if self._device_serving() else self._cpu

    def _guard(self, op: str, fn: Callable):
        """One supervised device interaction: buggify fault injection first
        (simulation), then the bounded watchdog around the real call."""
        if buggify("device.lost"):
            raise DeviceLost("buggify: device lost")
        if op in ("dispatch", "readback") and buggify("device.dispatch_hang"):
            raise DeviceHang(
                f"buggify: dispatch hung past watchdog {self.watchdog_s:.0f}s"
            )
        if op in ("dispatch", "probe") and buggify("device.compile_fail"):
            raise DeviceCompileFail("buggify: kernel compile failed")
        return self._watchdog.run(fn)

    def _inject_corrupt(self, verdicts: list):
        if buggify("device.readback_corrupt"):
            # garbage D2H bytes: out-of-enum codes that validate_verdicts
            # must catch (the detection path, not just the injection)
            return [7] * len(verdicts)
        return verdicts

    def _classify_or_reraise(self, op: str, e: Exception) -> None:
        """Device failures are absorbed and counted; caller bugs (bad
        versions / malformed ranges) re-raise — the supervisor must never
        turn an API misuse into a silent degrade."""
        if isinstance(e, VerdictValidationError):
            # malformed verdicts ARE a device failure (corrupt readback)
            self._note_failure(op, DeviceReadbackCorrupt(str(e)))
            return
        if isinstance(e, (ValueError, TypeError)) and not isinstance(e, DeviceError):
            raise e
        self._note_failure(op, e)

    def _note_failure(self, op: str, err) -> None:
        cls = classify_failure(err)
        self._last_failure = f"{op}:{cls}"
        self._fails += 1
        self._suspect = True
        self._parity_pending = False
        testcov(f"device.fail.{cls}")
        # first retry waits the knob value itself; doubling applies from
        # the second consecutive failure on
        self._next_attempt = self._clock() + (
            self._backoff if self._state != "degraded" else self.reprobe_interval
        )
        self._backoff = min(self._backoff * 2, self.max_backoff)
        if self._fails >= self.retry_limit and self._state != "degraded":
            self._trip()
        else:
            # keep the failure monitor current on every failure — a failed
            # re-probe must not leave it frozen at "probing"
            self._feed_failmon()

    def _note_success(self) -> None:
        self._fails = 0
        self._backoff = self.backoff0

    def _trip(self) -> None:
        """Circuit breaker: stop hammering the device, serve from the CPU
        reference, re-probe on the slow cadence."""
        self._drop_device()
        self._ensure_cpu()
        self._state = "degraded"
        self._trips += 1
        self._degraded_since = self._clock()
        self._next_attempt = self._clock() + self.reprobe_interval
        testcov("device.degraded")
        self._feed_failmon()

    def _drop_device(self) -> None:
        dev, self._dev = self._dev, None
        self._suspect = False
        if dev is not None:
            try:
                if hasattr(dev, "abandon_inflight"):
                    dev.abandon_inflight()
                dev.close()
            except Exception:  # noqa: BLE001 — it is being discarded
                pass

    def _feed_failmon(self) -> None:
        if self._failmon is not None and hasattr(self._failmon, "note_device"):
            self._failmon.note_device(self._failmon_name, self.health())

    # -- record / fallback ----------------------------------------------------
    def _record_batch(self, version: int, txns, verdicts) -> None:
        writes: list[tuple[bytes, bytes]] = []
        for tx, v in zip(txns, verdicts):
            if int(v) == int(Verdict.COMMITTED):
                writes.extend(tx.write_ranges)
        if writes:
            self._record.append((version, tuple(writes)))

    def _apply_record_floor(self, version: int) -> None:
        if version <= self._record_floor:
            return
        self._record_floor = version
        # writes at v < floor can never conflict again (any live snapshot
        # is >= floor > v): prune from the front (versions ascend)
        i = 0
        while i < len(self._record) and self._record[i][0] < version:
            i += 1
        if i:
            del self._record[:i]

    def _replay_record(self, cs: ConflictSet) -> None:
        """Rebuild a backend from the committed-write record: write-only
        batches (no reads => no conflicts, no TooOld dependence) commute
        with GC, so replaying every batch at floor 0 and applying the
        record floor once at the end reconstructs the exact step function."""
        for version, writes in self._record:
            cs.resolve_batch(
                version,
                [TxInfo(read_snapshot=version - 1, read_ranges=(),
                        write_ranges=writes)],
            )
        if self._record_floor > cs.oldest_version:
            cs.remove_before(self._record_floor)

    def _ensure_cpu(self) -> ConflictSet:
        if self._cpu is None:
            cs = self._fallback_factory(0)
            self._replay_record(cs)
            if not self._window and self._floor > cs.oldest_version:
                cs.remove_before(self._floor)
            self._cpu = cs
            testcov("device.cpu_rebuild")
        return self._cpu

    def _cpu_resolve(self, commit_version: int, txns) -> list[Verdict]:
        verdicts = self._ensure_cpu().resolve_batch(commit_version, txns)
        self._record_batch(commit_version, txns, verdicts)
        return verdicts

    # -- deferred window ------------------------------------------------------
    def _wait_entry(self, entry: _WinEntry) -> list[Verdict]:
        if entry.result is not None:
            if entry.txns:
                # completed but not yet recorded (a CompletedResolve behind
                # a still-in-flight predecessor): try to finish the prefix
                # so the record never interleaves out of version order
                self._complete_prefix(entry)
            return list(entry.result)
        try:
            verdicts = self._guard("readback", entry.inner.wait)
            verdicts = self._inject_corrupt(verdicts)
            validate_verdicts(verdicts, len(entry.txns))
        except Exception as e:  # noqa: BLE001 — classified or re-raised
            self._classify_or_reraise("readback", e)
            self._recover_window()
            assert entry.result is not None
            return list(entry.result)
        entry.result = list(verdicts)
        self._note_success()
        self._complete_prefix(entry)
        return list(entry.result)

    def _entry_done(self, e: _WinEntry) -> bool:
        """True if e's inner verdicts are already host-resident (the device
        mixin drains in dispatch order, so waiting a later handle settles
        earlier ones)."""
        if isinstance(e.inner, CompletedResolve):
            return True
        return getattr(e.inner, "_result", None) is not None

    def _complete_prefix(self, upto: _WinEntry) -> None:
        """Record (in dispatch order) every window entry whose verdicts are
        now known, through `upto`; then pop the recorded prefix."""
        for e in self._window:
            if e.result is None:
                if e is upto or self._entry_done(e):
                    # `upto` was validated by the caller; earlier settled
                    # entries are fetched here and must pass the SAME
                    # validation (and chaos injection) — a corrupt
                    # readback must never slip through this side door
                    verdicts = self._inject_corrupt(list(e.inner.wait()))
                    try:
                        validate_verdicts(verdicts, len(e.txns))
                    except ValueError as ex:
                        self._note_failure(
                            "readback", DeviceReadbackCorrupt(str(ex))
                        )
                        self._recover_window()
                        return
                    e.result = verdicts
                else:
                    break
            if e.txns:
                self._record_batch(e.version, e.txns, e.result)
                e.txns = ()
            if e is upto:
                break
        self._settle_window()

    def _settle_window(self) -> None:
        """Pop the recorded prefix, applying each popped entry's deferred
        GC floors to the record — those floors were issued after the entry
        resolved and before its successor dispatched, so once the entry is
        recorded every remaining batch was dispatched above them."""
        while (
            self._window
            and self._window[0].result is not None
            and not self._window[0].txns
        ):
            e = self._window.pop(0)
            for g in e.gc_after:
                self._apply_record_floor(g)
        if not self._window:
            self._apply_record_floor(self._floor)

    def _recover_window(self) -> None:
        """Full device loss with a deferred window open: rebuild the CPU
        reference from the record (pruned only to the pre-window floor),
        then replay every open batch in dispatch order with its recorded
        GC interleaving — completed batches re-apply their known committed
        writes, uncompleted ones get their verdicts from the CPU replay.
        The verdict stream is identical to what a healthy device would
        have produced (the CPU reference IS the parity oracle the device
        kernel is tested against)."""
        cpu = self._ensure_cpu()  # while the window is still visible
        if not self._window:
            return
        testcov("device.window_recover")
        window, self._window = self._window, []
        for e in window:
            if e.result is None:
                e.result = cpu.resolve_batch(e.version, e.txns)
                self._record_batch(e.version, e.txns, e.result)
                e.txns = ()
            elif e.txns:
                # verdicts were read (device-validated) but not recorded:
                # re-apply the committed writes to the rebuilt CPU set
                cpu.resolve_batch(
                    e.version,
                    [
                        TxInfo(e.version - 1, (), tx.write_ranges)
                        for tx, v in zip(e.txns, e.result)
                        if int(v) == int(Verdict.COMMITTED)
                    ],
                )
                self._record_batch(e.version, e.txns, e.result)
                e.txns = ()
            for g in e.gc_after:
                cpu.remove_before(g)
                self._apply_record_floor(g)
        self._apply_record_floor(self._floor)
        if cpu.oldest_version < self._floor:
            cpu.remove_before(self._floor)

    # -- re-probe / promotion -------------------------------------------------
    def _maybe_attempt_device(self) -> None:
        if self._device_serving() or self._forced:
            return
        if self._clock() < self._next_attempt:
            return
        self._try_promote()

    def _try_promote(self) -> None:
        """Probe the device and hand state back up: fresh backend, record
        replay, then arm the parity check — the promotion is trusted only
        once the first promoted batch's abort set matches the CPU's."""
        self._probes += 1
        prev_state, self._state = self._state, "probing"
        self._feed_failmon()
        testcov("device.probe")
        try:
            self._drop_device()
            dev = self._guard("probe", lambda: self._device_factory(0))
            self._guard("probe", dev.healthcheck)
            self._dev = dev
            self._guard("promote", lambda: self._replay_record(dev))
            if self._floor > dev.oldest_version:
                self._guard("gc", lambda: dev.remove_before(self._floor))
        except Exception as e:  # noqa: BLE001 — classified device failure
            self._drop_device()
            self._state = prev_state
            self._note_failure("probe", e)
            return
        self._state = prev_state  # healthy only after the parity batch
        self._suspect = False
        self._parity_pending = True

    def _resolve_parity(self, commit_version: int, txns) -> list[Verdict]:
        """First post-promotion batch: device and CPU reference both
        resolve it and the abort sets must agree bit-for-bit before the
        device is trusted (state-handoff verification).  The CPU's
        verdicts are what gets served either way, so even a lying device
        aborts nothing in error.  An EMPTY batch proves nothing — the
        check stays armed until the first batch with transactions."""
        vacuous = len(txns) == 0
        cpu = self._ensure_cpu()
        dev_verdicts = None
        try:
            dev_verdicts = self._guard(
                "dispatch",
                lambda: self._dev.resolve_batch(commit_version, txns),
            )
            dev_verdicts = self._inject_corrupt(dev_verdicts)
            validate_verdicts(dev_verdicts, len(txns))
        except Exception as e:  # noqa: BLE001 — classified or re-raised
            # a re-raised caller bug leaves the parity check ARMED: the
            # device must not become trusted off a batch that never ran
            self._classify_or_reraise("promote", e)
            dev_verdicts = None
        self._parity_pending = False
        cpu_verdicts = cpu.resolve_batch(commit_version, txns)
        self._record_batch(commit_version, txns, cpu_verdicts)
        if dev_verdicts is None:
            return cpu_verdicts
        if [int(v) for v in dev_verdicts] != [int(v) for v in cpu_verdicts]:
            self._note_failure(
                "promote",
                DeviceReadbackCorrupt(
                    "post-promotion parity mismatch vs CPU reference"
                ),
            )
            return cpu_verdicts
        if vacuous:
            self._parity_pending = True  # nothing was verified; stay armed
            return cpu_verdicts
        # parity holds: the device is authoritative again.  Drop the CPU
        # set (the record stays — it is the rebuild source for the NEXT
        # degrade) and close the degraded-time accounting window.
        self._promotions += 1
        if self._degraded_since is not None:
            self._time_degraded += self._clock() - self._degraded_since
            self._degraded_since = None
        self._state = "healthy"
        self._note_success()
        cpu_set, self._cpu = self._cpu, None
        try:
            cpu_set.close()
        except Exception:  # noqa: BLE001
            pass
        testcov("device.promoted")
        self._feed_failmon()
        return cpu_verdicts
