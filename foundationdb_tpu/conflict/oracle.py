"""Pure-Python interval-map oracle — the abort-set parity referee.

Port of the *semantics* (not the code) of the reference's SlowConflictSet
(fdbserver/SkipList.cpp:59-88): a step function over the key space mapping
each key to the newest commit version that wrote it, kept as a sorted list of
(boundary, version) pairs.  A read range [b, e) at snapshot s conflicts iff
max{version over [b, e)} > s.  Deliberately simple and obviously correct;
used by tests to referee the native and TPU implementations.
"""

from __future__ import annotations

import bisect
import time
from typing import Sequence

from .api import ConflictSet, KernelStats, TxInfo, Verdict, validate_batch


class _StepFunction:
    """Piecewise-constant int over byte-string key space."""

    def __init__(self) -> None:
        self._keys: list[bytes] = [b""]
        self._vals: list[int] = [0]

    def query_max(self, begin: bytes, end: bytes) -> int:
        if begin >= end:
            return 0
        lo = bisect.bisect_right(self._keys, begin) - 1
        hi = bisect.bisect_left(self._keys, end)
        return max(self._vals[lo:hi])

    def assign(self, begin: bytes, end: bytes, version: int) -> None:
        """Set value over [begin, end) to `version` (plain assignment with
        boundary splitting; callers guarantee monotonically increasing
        versions — enforced in resolve_batch)."""
        if begin >= end:
            return
        ks, vs = self._keys, self._vals
        # value just right of `end` must be preserved: split at end
        hi = bisect.bisect_right(ks, end) - 1
        end_val = vs[hi]
        lo = bisect.bisect_right(ks, begin) - 1
        # remove boundaries strictly inside (begin, end), insert begin/end
        i0 = lo + 1 if ks[lo] < begin else lo
        new_keys = ks[:i0] + [begin, end]
        new_vals = vs[:i0] + [version, end_val]
        j = bisect.bisect_right(ks, end)  # boundaries strictly after end kept
        new_keys += ks[j:]
        new_vals += vs[j:]
        self._keys, self._vals = new_keys, new_vals
        self._coalesce()

    def _coalesce(self) -> None:
        ks, vs = self._keys, self._vals
        nk, nv = [ks[0]], [vs[0]]
        for k, v in zip(ks[1:], vs[1:]):
            if v != nv[-1]:
                nk.append(k)
                nv.append(v)
        self._keys, self._vals = nk, nv

    def clamp_below(self, floor: int) -> None:
        self._vals = [0 if v < floor else v for v in self._vals]
        self._coalesce()


def ranges_overlap(a: tuple[bytes, bytes], b: tuple[bytes, bytes]) -> bool:
    return a[0] < b[1] and b[0] < a[1]


class OracleConflictSet(ConflictSet):
    def __init__(self, oldest_version: int = 0) -> None:
        self._history = _StepFunction()
        self._oldest = oldest_version
        self._last_commit = oldest_version
        self.stats = KernelStats(backend="oracle")

    @property
    def oldest_version(self) -> int:
        return self._oldest

    @property
    def node_count(self) -> int:
        return len(self._history._keys)

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        validate_batch(commit_version, txns, self._oldest)
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit_version {commit_version} not after last batch {self._last_commit}"
                " (versions are assigned monotonically by the sequencer,"
                " reference masterserver.actor.cpp:831)"
            )
        self._last_commit = commit_version
        t0 = time.perf_counter()
        verdicts: list[Verdict] = []
        batch_writes = _StepFunction()  # committed-so-far within this batch
        committed_writes: list[tuple[bytes, bytes]] = []
        for t in txns:
            if t.read_snapshot < self._oldest:
                verdicts.append(Verdict.TOO_OLD)
                continue
            conflict = False
            for b, e in t.read_ranges:
                if b >= e:
                    continue
                if self._history.query_max(b, e) > t.read_snapshot:
                    conflict = True
                    break
                if batch_writes.query_max(b, e) > 0:
                    conflict = True
                    break
            if conflict:
                verdicts.append(Verdict.CONFLICT)
                continue
            verdicts.append(Verdict.COMMITTED)
            for b, e in t.write_ranges:
                batch_writes.assign(b, e, 1)
                committed_writes.append((b, e))
        for b, e in committed_writes:
            self._history.assign(b, e, commit_version)
        rows = sum(len(t.read_ranges) + len(t.write_ranges) for t in txns)
        self.stats.real_rows += rows
        self.stats.padded_rows += rows  # no padding in the oracle
        self.stats.note_batch(
            len(txns),
            sum(1 for v in verdicts if v == Verdict.CONFLICT),
            time.perf_counter() - t0,
        )
        return verdicts

    def remove_before(self, version: int) -> None:
        if version > self._oldest:
            self._oldest = version
            t0 = time.perf_counter()
            before = len(self._history._keys)
            self._history.clamp_below(version)
            self.stats.gc_calls += 1
            self.stats.rows_reclaimed += max(0, before - len(self._history._keys))
            self.stats.merge_s += time.perf_counter() - t0
