"""Default native (C++ skip list) conflict backend: lazy build + plugin load.

The CPU baseline implementation (native/conflictset.cpp) compiled on first
use and loaded through the plugin seam (plugin.py).  This is the performance
bar the device kernel is benchmarked against — the stand-in for the
reference's fdbserver/SkipList.cpp running on a host core.
"""

from __future__ import annotations

import pathlib
import subprocess
import threading

from .plugin import ConflictPlugin

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_LIB = _NATIVE_DIR / "libfdbtpu_conflict.so"
_lock = threading.Lock()
_plugin: ConflictPlugin | None = None


def build_native(force: bool = False) -> pathlib.Path:
    src = _NATIVE_DIR / "conflictset.cpp"
    with _lock:
        if force or not _LIB.exists() or _LIB.stat().st_mtime < src.stat().st_mtime:
            proc = subprocess.run(
                ["make", "-s", "-C", str(_NATIVE_DIR)],
                capture_output=True,
                text=True,
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f"native conflict backend build failed:\n{proc.stderr}"
                )
    return _LIB


def native_plugin() -> ConflictPlugin:
    global _plugin
    if _plugin is None:
        _plugin = ConflictPlugin(str(build_native()))
    return _plugin


def NativeConflictSet(oldest_version: int = 0):
    """Factory matching the other backends' constructors."""
    return native_plugin().create(oldest_version)
