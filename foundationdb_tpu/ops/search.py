"""Vectorized multiword-key comparison and binary search.

The device-resident conflict state keeps boundary keys as uint32[cap, W]
word vectors (see keys.py).  History conflict checks need, per query key,
lower/upper bounds into that sorted array — the TPU replacement for the
reference's skip-list descent (fdbserver/SkipList.cpp:408-460 `find`).
Fixed-trip-count binary search: log2(cap) vectorized gather+compare rounds,
no data-dependent control flow, so XLA compiles it to a tight loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .rmq import _levels


def lex_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a < b over trailing word axis; [..., W] -> [...] bool."""
    W = a.shape[-1]
    lt = jnp.zeros(a.shape[:-1], dtype=bool)
    eq = jnp.ones(a.shape[:-1], dtype=bool)
    for w in range(W):
        aw, bw = a[..., w], b[..., w]
        lt = lt | (eq & (aw < bw))
        eq = eq & (aw == bw)
    return lt


def lex_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def _search(sorted_keys: jnp.ndarray, q: jnp.ndarray, go_right) -> jnp.ndarray:
    n = sorted_keys.shape[0]
    if n == 0:
        return jnp.zeros(q.shape[0], dtype=jnp.int32)
    steps = _levels(n)

    # fori_loop rather than Python unrolling: the body compiles once, keeping
    # XLA compile time flat in log(n) (unrolled, ~10 searches dominated the
    # whole conflict kernel's compile).
    def body(_, state):
        lo, hi = state
        active = lo < hi
        mid = jnp.clip((lo + hi) // 2, 0, n - 1)
        km = jnp.take(sorted_keys, mid, axis=0)
        right = go_right(km, q)
        lo = jnp.where(active & right, mid + 1, lo)
        hi = jnp.where(active & ~right, mid, hi)
        return lo, hi

    lo = jnp.zeros(q.shape[0], dtype=jnp.int32)
    hi = jnp.full(q.shape[0], n, dtype=jnp.int32)
    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def lower_bound(sorted_keys: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """First index i with sorted_keys[i] >= q.  sorted_keys [N, W], q [Q, W]."""
    return _search(sorted_keys, q, lambda km, qq: lex_less(km, qq))


def upper_bound(sorted_keys: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """First index i with sorted_keys[i] > q."""
    return _search(sorted_keys, q, lambda km, qq: ~lex_less(qq, km))
