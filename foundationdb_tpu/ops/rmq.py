"""Static-shape range-max/min machinery for the conflict kernel.

Two dual primitives, both O(N log N) fully-vectorized ops:

  * sparse table (range query, point values): answers max/min over [lo, hi)
    in O(1) gathers per query — replaces the reference skip list's per-level
    max-version pyramid (fdbserver/SkipList.cpp:795-831) for the history
    check "newest committed write version over this read range".
  * block decomposition (range update, point query): each interval update
    [lo, hi) with value v lands as two power-of-two block updates at level
    floor(log2(hi-lo)); a down-sweep pushes levels to points.  min/max are
    idempotent so colliding scatter updates need no dedup.  Used to compute,
    per endpoint-gap, the earliest (min-index) transaction writing that gap —
    the device formulation of MiniConflictSet's ordered bitmask walk
    (fdbserver/SkipList.cpp:1028-1152).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# Python ints, not jnp scalars: as jit-time constants they fold into the
# compiled program; device-array identities made TPU sparse-table builds
# ~5x slower (the concat pads became runtime broadcasts).
U32_MAX = 0xFFFFFFFF
I32_MAX = 0x7FFFFFFF


def _levels(n: int) -> int:
    return max(1, int(np.ceil(np.log2(max(n, 2)))) + 1)


def floor_log2(x: jnp.ndarray) -> jnp.ndarray:
    """floor(log2(x)) for x >= 1 (int32)."""
    return jnp.int32(31) - jax.lax.clz(x.astype(jnp.int32))


def build_sparse_table(vals: jnp.ndarray, op, ident) -> jnp.ndarray:
    """table[l, i] = op-reduce of vals[i : i + 2**l] (identity-padded).

    vals: [N]; returns [L, N]."""
    n = vals.shape[0]
    levels = [vals]
    for l in range(1, _levels(n)):
        s = 1 << (l - 1)
        prev = levels[-1]
        shifted = jnp.concatenate([prev[s:], jnp.full((min(s, n),), ident, prev.dtype)])[:n]
        levels.append(op(prev, shifted))
    return jnp.stack(levels)


def query_sparse_table(table: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray, op, ident) -> jnp.ndarray:
    """op-reduce over [lo, hi) per query; empty ranges (hi <= lo) -> ident."""
    n = table.shape[1]
    nonempty = hi > lo
    length = jnp.maximum(hi - lo, 1)
    k = floor_log2(length)
    pw = (jnp.int32(1) << k)
    i1 = jnp.clip(lo, 0, n - 1)
    i2 = jnp.clip(hi - pw, 0, n - 1)
    a = table[k, i1]
    b = table[k, i2]
    out = op(a, b)
    return jnp.where(nonempty, out, jnp.asarray(ident, table.dtype))


def range_update_point_query(
    n: int, lo: jnp.ndarray, hi: jnp.ndarray, val: jnp.ndarray, mask: jnp.ndarray, op_name: str, ident
) -> jnp.ndarray:
    """out[g] = op over {val[j] : mask[j] and lo[j] <= g < hi[j]} (else ident).

    op_name: "min" or "max" (idempotent, so colliding updates are safe).
    Returns [n]."""
    L = _levels(n)
    length = jnp.maximum(hi - lo, 1)
    k = jnp.where(mask, floor_log2(length), 0)
    pw = jnp.int32(1) << k
    v = jnp.where(mask, val, jnp.asarray(ident, val.dtype))
    p1 = jnp.clip(jnp.where(mask, lo, 0), 0, n - 1)
    p2 = jnp.clip(jnp.where(mask, hi - pw, 0), 0, n - 1)
    block = jnp.full((L, n), ident, dtype=val.dtype)
    if op_name == "min":
        block = block.at[k, p1].min(v).at[k, p2].min(v)
        op = jnp.minimum
    elif op_name == "max":
        block = block.at[k, p1].max(v).at[k, p2].max(v)
        op = jnp.maximum
    else:
        raise ValueError(op_name)
    # down-sweep: level l block at i covers [i, i+2**l); push to the two
    # half-blocks at level l-1 (positions i and i + 2**(l-1)); shifted[i] is
    # the level-l contribution arriving from position i - 2**(l-1).
    acc = block[L - 1]
    for l in range(L - 1, 0, -1):
        s = 1 << (l - 1)
        shifted = jnp.concatenate([jnp.full((min(s, n),), ident, acc.dtype), acc[: max(n - s, 0)]])[:n]
        acc = op(block[l - 1], op(acc, shifted))
    return acc
