"""Multi-resolver conflict resolution over a TPU device mesh.

The reference scales OCC by splitting the key space into contiguous
partitions, one per Resolver process: the proxy routes each transaction's
conflict ranges to the resolvers whose partition they intersect
(fdbserver/MasterProxyServer.actor.cpp:280-320 ResolutionRequestBuilder) and
merges the per-resolver verdicts with min() (:558-569).  Crucially each
resolver decides *from its own partition alone* and inserts the write ranges
of transactions it locally judged committed — even if another resolver
aborts that transaction (a deliberate false-positive source the reference
accepts; see Resolver.actor.cpp).  That independence is exactly what makes
the check SPMD:

  mesh axis "resolvers": device i owns key partition [split[i], split[i+1])
  - batch tensors are replicated to all devices (host broadcast — the
    device-side analog of the proxy fanning the batch out over the network)
  - each device clips every range to its partition; ranges that miss the
    partition become padding
  - each device runs the identical single-partition kernel
    (conflict/device.py resolve_core) on its clipped view and local state
  - verdicts merge with lax.pmin over the axis (CONFLICT=0 < TOO_OLD=1 <
    COMMITTED=2, matching the reference enum ConflictSet.h:36-40 — the
    min-combine's load-bearing ordering) — ONE collective per batch,
    riding ICI.

State stays resident per device (the partition's step function), so the
only per-batch transfers are the batch tensors in and B verdicts out.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import keys as keymod
from ..conflict.api import ConflictSet, TxInfo, Verdict, validate_batch
from ..conflict.device import _SENT_WORD, N_BUCKETS, pack_batch, resolve_core
from ..ops.rmq import _levels
from ..ops.search import lex_less

RESOLVER_AXIS = "resolvers"


def make_resolver_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} resolver devices, only {len(devs)} available")
    return Mesh(np.array(devs[:n]), (RESOLVER_AXIS,))


def _lex_max(a, b):
    """Rowwise lexicographic max of uint32[..., W] keys."""
    return jnp.where(lex_less(a, b)[..., None], b, a)


def _lex_min(a, b):
    return jnp.where(lex_less(b, a)[..., None], b, a)


def _clip_ranges(b, e, tx, lo_row, hi_row):
    """Clip ranges [b, e) to the partition [lo_row, hi_row); ranges that
    miss the partition become sentinel padding with tx = -1 (the device-side
    ResolutionRequestBuilder: only intersecting ranges reach a resolver)."""
    cb = _lex_max(b, lo_row[None, :])
    ce = _lex_min(e, hi_row[None, :])
    live = lex_less(cb, ce) & (tx >= 0)
    sent = jnp.full_like(b, _SENT_WORD)
    return (
        jnp.where(live[:, None], cb, sent),
        jnp.where(live[:, None], ce, sent),
        jnp.where(live, tx, -1),
    )


def _sharded_resolve(
    ks, vs, cnt,  # per-device state shards: [1, CAP, W], [1, CAP], [1]
    lo, hi,  # per-device partition bounds: [1, W] each
    rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off,  # replicated batch
    *, cap, n_txn, n_read, n_write,
):
    ks, vs, lo, hi = ks[0], vs[0], lo[0], hi[0]
    rb, re_, r_tx = _clip_ranges(rb, re_, r_tx, lo, hi)
    wb, we, w_tx = _clip_ranges(wb, we, w_tx, lo, hi)
    # full-depth search (bucket index unused at full depth): partition caps
    # are small, and it keeps the sharded path free of fallback control flow
    dummy_bidx = jnp.zeros(N_BUCKETS + 1, jnp.int32)
    verdict, new_ks, new_vs, new_count, _bidx, _conv, _ok = resolve_core(
        ks, vs, dummy_bidx, cnt[0], rb, re_, r_tx, wb, we, w_tx, snap, active,
        commit_off,
        cap=cap, n_txn=n_txn, n_read=n_read, n_write=n_write,
        search_iters=_levels(cap) + 1,
    )
    # proxy min-combine (MasterProxyServer.actor.cpp:558-569) over ICI
    merged = jax.lax.pmin(verdict, RESOLVER_AXIS)
    return merged, new_ks[None], new_vs[None], new_count[None]


@jax.jit
def _sharded_gc(vs, off):
    """remove_before on the sharded gap-version array: elementwise rebase,
    so the output inherits the input's sharding — compiled once, offset is
    a runtime argument (same pattern as conflict/device.py _gc_kernel)."""
    return jnp.maximum(vs - off, 0)


def build_sharded_resolver(mesh: Mesh, *, cap: int, n_txn: int, n_read: int, n_write: int):
    """Jit-compiled sharded resolve step for fixed bucket sizes."""
    shard = P(RESOLVER_AXIS)
    repl = P()
    fn = jax.shard_map(
        functools.partial(
            _sharded_resolve, cap=cap, n_txn=n_txn, n_read=n_read, n_write=n_write
        ),
        mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard) + (repl,) * 9,
        out_specs=(repl, shard, shard, shard),
        # the kernel's loop carries start replicated and become varying;
        # skip the static replication check rather than pcast every carry
        check_vma=False,
    )
    return jax.jit(fn)


class ShardedDeviceConflictSet(ConflictSet):
    """Key-partitioned ConflictSet over an N-device mesh.

    Equivalent to N reference Resolvers plus the proxy's verdict merge, with
    the partition split points fixed at construction (the reference
    rebalances online via masterserver.actor.cpp:964 resolutionBalancing;
    here rebalancing = build a new instance with new splits — resolver state
    evaporates on generation change anyway, SURVEY §5 failure detection).
    """

    def __init__(
        self,
        mesh: Mesh,
        split_keys: Sequence[bytes],
        oldest_version: int = 0,
        *,
        max_key_bytes: int = keymod.DEFAULT_MAX_KEY_BYTES,
        capacity: int = 1 << 14,
    ) -> None:
        n = mesh.devices.size
        if len(split_keys) != n - 1:
            raise ValueError(f"need {n - 1} split keys for {n} resolver devices")
        if list(split_keys) != sorted(split_keys) or len(set(split_keys)) != len(split_keys):
            raise ValueError("split keys must be strictly increasing")
        self._mesh = mesh
        self._n = n
        self._max_key_bytes = max_key_bytes
        self._W = W = keymod.num_words(max_key_bytes)
        self._cap = capacity
        self._base = oldest_version
        self._oldest = oldest_version
        self._last_commit = oldest_version
        self._fns: dict[tuple[int, int, int], object] = {}

        bounds = [b""] + list(split_keys)
        lo = keymod.encode_keys(bounds, max_key_bytes)
        hi = np.empty_like(lo)
        hi[:-1] = lo[1:]
        hi[-1] = keymod.sentinel(max_key_bytes)
        ks = np.full((n, capacity, W), _SENT_WORD, dtype=np.uint32)
        ks[:, 0, :] = lo  # each partition's step function starts at its own floor
        vs = np.zeros((n, capacity), dtype=np.int32)

        self._state_sharding = NamedSharding(mesh, P(RESOLVER_AXIS))
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        self._lo, self._hi = dev(lo), dev(hi)
        self._ks, self._vs = dev(ks), dev(vs)
        self._counts = np.ones(n, dtype=np.int64)
        self._dev_counts = dev(np.ones(n, dtype=np.int32))

    @property
    def oldest_version(self) -> int:
        return self._oldest

    def _offset(self, version: int) -> int:
        off = version - self._base
        if off >= 2**31 - 2**24:
            raise OverflowError("version offset overflow; call remove_before")
        return max(off, 0)

    def _fn(self, n_txn: int, n_read: int, n_write: int):
        key = (n_txn, n_read, n_write)
        if key not in self._fns:
            self._fns[key] = build_sharded_resolver(
                self._mesh, cap=self._cap, n_txn=n_txn, n_read=n_read, n_write=n_write
            )
        return self._fns[key]

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        validate_batch(commit_version, txns, self._oldest)
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit_version {commit_version} not after last batch {self._last_commit}"
            )
        B = len(txns)
        if B == 0:
            self._last_commit = commit_version
            return []
        rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, Bp = pack_batch(
            txns, self._oldest, self._offset, self._max_key_bytes
        )
        R, Wn = rbv.shape[0], wbv.shape[0]

        fn = self._fn(Bp, R, Wn)
        verdict, new_ks, new_vs, new_counts = fn(
            self._ks, self._vs, self._dev_counts, self._lo, self._hi,
            rbv, rev, rtv, wbv, wev, wtv,
            snap_p, active_p, np.int32(self._offset(commit_version)),
        )
        counts = np.asarray(new_counts)
        if counts.max() > self._cap:
            raise RuntimeError(
                f"partition boundary overflow ({counts.max()} > cap {self._cap}); "
                "raise capacity or remove_before more often"
            )
        self._ks, self._vs, self._counts = new_ks, new_vs, counts
        self._dev_counts = new_counts
        self._last_commit = commit_version
        codes = np.asarray(verdict)[:B]
        return [Verdict(int(c)) for c in codes]

    def remove_before(self, version: int) -> None:
        if version <= self._oldest:
            return
        self._oldest = version
        off = version - self._base
        if off > 0:
            self._vs = _sharded_gc(self._vs, np.int32(off))
            self._base = version
