"""Multi-resolver conflict resolution over a TPU device mesh.

The reference scales OCC by splitting the key space into contiguous
partitions, one per Resolver process: the proxy routes each transaction's
conflict ranges to the resolvers whose partition they intersect
(fdbserver/MasterProxyServer.actor.cpp:280-320 ResolutionRequestBuilder) and
merges the per-resolver verdicts with min() (:558-569).  Crucially each
resolver decides *from its own partition alone* and inserts the write ranges
of transactions it locally judged committed — even if another resolver
aborts that transaction (a deliberate false-positive source the reference
accepts; see Resolver.actor.cpp).  That independence is exactly what makes
the check SPMD:

  mesh axis "resolvers": device i owns key partition [split[i], split[i+1])
  - batch tensors are replicated to all devices (host broadcast — the
    device-side analog of the proxy fanning the batch out over the network)
  - each device clips every range to its partition; ranges that miss the
    partition become padding
  - each device runs the identical single-partition kernel
    (conflict/device.py resolve_core) on its clipped view and local state
  - verdicts merge with lax.pmin over the axis (CONFLICT=0 < TOO_OLD=1 <
    COMMITTED=2, matching the reference enum ConflictSet.h:36-40 — the
    min-combine's load-bearing ordering) — ONE collective per batch,
    riding ICI.

State stays resident per device (the partition's step function), so the
only per-batch transfers are the batch tensors in and B verdicts out.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import keys as keymod
from ..conflict.api import ConflictSet, TxInfo, Verdict, validate_batch
from ..conflict.device import (
    _SENT_WORD,
    FAST_SEARCH_ITERS,
    host_bucket_index,
    impl_from_env,
    pack_batch,
    resolve_core,
)
from ..ops.rmq import _levels
from ..ops.search import lex_less

RESOLVER_AXIS = "resolvers"


def make_resolver_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} resolver devices, only {len(devs)} available")
    return Mesh(np.array(devs[:n]), (RESOLVER_AXIS,))


def _lex_max(a, b):
    """Rowwise lexicographic max of uint32[..., W] keys."""
    return jnp.where(lex_less(a, b)[..., None], b, a)


def _lex_min(a, b):
    return jnp.where(lex_less(b, a)[..., None], b, a)


def _clip_ranges(b, e, tx, lo_row, hi_row):
    """Clip ranges [b, e) to the partition [lo_row, hi_row); ranges that
    miss the partition become sentinel padding with tx = -1 (the device-side
    ResolutionRequestBuilder: only intersecting ranges reach a resolver)."""
    cb = _lex_max(b, lo_row[None, :])
    ce = _lex_min(e, hi_row[None, :])
    live = lex_less(cb, ce) & (tx >= 0)
    sent = jnp.full_like(b, _SENT_WORD)
    return (
        jnp.where(live[:, None], cb, sent),
        jnp.where(live[:, None], ce, sent),
        jnp.where(live, tx, -1),
    )


def _sharded_resolve(
    ks, vs, cnt, bidx,  # per-device state shards: [1, CAP, W], [1, CAP], [1], [1, NB+1]
    lo, hi,  # per-device partition bounds: [1, W] each
    rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off,  # replicated batch
    ok_in,  # replicated bool: validity accumulated across a pipelined stream
    *, cap, n_txn, n_read, n_write, search_iters, merge_impl, search_impl,
):
    ks, vs, lo, hi, bidx = ks[0], vs[0], lo[0], hi[0], bidx[0]
    rb, re_, r_tx = _clip_ranges(rb, re_, r_tx, lo, hi)
    wb, we, w_tx = _clip_ranges(wb, we, w_tx, lo, hi)
    verdict, new_ks, new_vs, new_count, new_bidx, conv, ok = resolve_core(
        ks, vs, bidx, cnt[0], rb, re_, r_tx, wb, we, w_tx, snap, active,
        commit_off, ok_in,
        cap=cap, n_txn=n_txn, n_read=n_read, n_write=n_write,
        search_iters=search_iters, merge_impl=merge_impl,
        search_impl=search_impl,
    )
    # proxy min-combine (MasterProxyServer.actor.cpp:558-569) over ICI; the
    # convergence / stream-validity flags fold the same way (all devices must
    # agree before a batch's verdicts are trusted)
    merged = jax.lax.pmin(verdict, RESOLVER_AXIS)
    all_conv = jax.lax.pmin(conv.astype(jnp.int32), RESOLVER_AXIS) > 0
    all_ok = jax.lax.pmin(ok.astype(jnp.int32), RESOLVER_AXIS) > 0
    return merged, new_ks[None], new_vs[None], new_count[None], new_bidx[None], all_conv, all_ok


@jax.jit
def _sharded_gc(vs, off):
    """remove_before on the sharded gap-version array: elementwise rebase,
    so the output inherits the input's sharding — compiled once, offset is
    a runtime argument (same pattern as conflict/device.py _gc_kernel)."""
    return jnp.maximum(vs - off, 0)


def build_sharded_resolver(
    mesh: Mesh, *, cap: int, n_txn: int, n_read: int, n_write: int,
    search_iters: int, merge_impl: str | None = None,
    search_impl: str | None = None,
):
    """Jit-compiled sharded resolve step for fixed bucket sizes."""
    merge_impl = impl_from_env("merge", merge_impl)
    search_impl = impl_from_env("search", search_impl)
    shard = P(RESOLVER_AXIS)
    repl = P()
    fn = jax.shard_map(
        functools.partial(
            _sharded_resolve, cap=cap, n_txn=n_txn, n_read=n_read,
            n_write=n_write, search_iters=search_iters, merge_impl=merge_impl,
            search_impl=search_impl,
        ),
        mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard, shard) + (repl,) * 10,
        out_specs=(repl, shard, shard, shard, shard, repl, repl),
        # the kernel's loop carries start replicated and become varying;
        # skip the static replication check rather than pcast every carry
        check_vma=False,
    )
    return jax.jit(fn)


class ShardedDeviceConflictSet(ConflictSet):
    """Key-partitioned ConflictSet over an N-device mesh.

    Equivalent to N reference Resolvers plus the proxy's verdict merge, with
    the partition split points fixed at construction (the reference
    rebalances online via masterserver.actor.cpp:964 resolutionBalancing;
    here rebalancing = build a new instance with new splits — resolver state
    evaporates on generation change anyway, SURVEY §5 failure detection).
    """

    def __init__(
        self,
        mesh: Mesh,
        split_keys: Sequence[bytes],
        oldest_version: int = 0,
        *,
        max_key_bytes: int = keymod.DEFAULT_MAX_KEY_BYTES,
        capacity: int = 1 << 14,
        merge_impl: str | None = None,
        search_impl: str | None = None,
    ) -> None:
        self._merge_impl = impl_from_env("merge", merge_impl)
        self._search_impl = impl_from_env("search", search_impl)
        n = mesh.devices.size
        if len(split_keys) != n - 1:
            raise ValueError(f"need {n - 1} split keys for {n} resolver devices")
        if list(split_keys) != sorted(split_keys) or len(set(split_keys)) != len(split_keys):
            raise ValueError("split keys must be strictly increasing")
        self._mesh = mesh
        self._n = n
        self._max_key_bytes = max_key_bytes
        self._W = keymod.num_words(max_key_bytes)
        self._base = oldest_version
        self._oldest = oldest_version
        self._last_commit = oldest_version
        self._fns: dict[tuple[int, int, int, int, int], object] = {}
        self.search_fallbacks = 0
        self.regrows = 0

        bounds = [b""] + list(split_keys)
        lo = keymod.encode_keys(bounds, max_key_bytes)
        hi = np.empty_like(lo)
        hi[:-1] = lo[1:]
        hi[-1] = keymod.sentinel(max_key_bytes)
        self._state_sharding = NamedSharding(mesh, P(RESOLVER_AXIS))
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        self._lo, self._hi = dev(lo), dev(hi)
        self._np_lo = lo
        self._init_state(capacity)

    def _init_state(self, capacity: int, ks=None, vs=None, counts=None) -> None:
        """Fresh (or regrown) per-partition state arrays."""
        n, W = self._n, self._W
        nks = np.full((n, capacity, W), _SENT_WORD, dtype=np.uint32)
        nvs = np.zeros((n, capacity), dtype=np.int32)
        if ks is None:
            nks[:, 0, :] = self._np_lo  # each partition starts at its own floor
            counts = np.ones(n, dtype=np.int64)
        else:
            c = min(ks.shape[1], capacity)
            nks[:, :c] = np.asarray(ks)[:, :c]
            nvs[:, :c] = np.asarray(vs)[:, :c]
        self._cap = capacity
        self._fns = {}  # cap is a static arg of the compiled step
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        self._ks, self._vs = dev(nks), dev(nvs)
        self._counts = np.asarray(counts, dtype=np.int64)
        self._counts_ub = self._counts.copy()
        self._dev_counts = dev(self._counts.astype(np.int32))
        if not hasattr(self, "_dev_ok"):
            # fresh construction only: a regrow must not reset the pipelined
            # validity accumulator (same contract as DeviceConflictSet)
            self._dev_ok = jax.device_put(
                np.asarray(True), NamedSharding(self._mesh, P())
            )
            self._pipelined_since_check = 0
        # word0-prefix bucket index per partition (sentinels -> last bucket)
        bidx = np.stack([host_bucket_index(nks[i]) for i in range(n)])
        self._bidx = dev(bidx)

    @property
    def oldest_version(self) -> int:
        return self._oldest

    def _offset(self, version: int) -> int:
        off = version - self._base
        if off >= 2**31 - 2**24:
            raise OverflowError("version offset overflow; call remove_before")
        return max(off, 0)

    def _fn(self, n_txn: int, n_read: int, n_write: int, search_iters: int):
        key = (
            self._cap, n_txn, n_read, n_write, search_iters,
            self._merge_impl, self._search_impl,
        )
        if key not in self._fns:
            self._fns[key] = build_sharded_resolver(
                self._mesh, cap=self._cap, n_txn=n_txn, n_read=n_read,
                n_write=n_write, search_iters=search_iters,
                merge_impl=self._merge_impl, search_impl=self._search_impl,
            )
        return self._fns[key]

    @property
    def capacity(self) -> int:
        return self._cap

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        validate_batch(commit_version, txns, self._oldest)
        B = len(txns)
        if B == 0:
            if commit_version <= self._last_commit:
                raise ValueError(
                    f"commit_version {commit_version} not after last batch "
                    f"{self._last_commit}"
                )
            self._last_commit = commit_version
            return []
        rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, Bp = pack_batch(
            txns, self._oldest, self._offset, self._max_key_bytes
        )
        codes = self.resolve_arrays(
            commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p
        )
        return [Verdict(int(c)) for c in codes[:B]]

    def resolve_arrays(
        self, commit_version: int, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
        sync: bool = True,
    ):
        """Packed fast path, mirroring DeviceConflictSet.resolve_arrays.

        sync=True: fetch verdicts; handle fast-search fallback (full-depth
        replay) and capacity regrow inline.

        sync=False: PIPELINED — dispatch and return the device verdict array
        without waiting; deferred convergence/capacity validity folds into a
        replicated device flag drained by check_pipelined()."""
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit_version {commit_version} not after last batch {self._last_commit}"
            )
        Bp, R, Wn = snap_p.shape[0], rbv.shape[0], wbv.shape[0]
        commit_off = np.int32(self._offset(commit_version))
        fast_iters = min(FAST_SEARCH_ITERS, _levels(self._cap) + 1)

        if not sync:
            # a batch adds at most 2*Wn boundaries per partition; if the
            # host-tracked upper bound could overflow, drain the pipeline —
            # and if genuinely near capacity, go through sync (which regrows)
            if self._counts_ub.max() + 2 * Wn > self._cap:
                self.check_pipelined()
                if self._counts_ub.max() + 2 * Wn > self._cap:
                    return np.asarray(
                        self.resolve_arrays(
                            commit_version, rbv, rev, rtv, wbv, wev, wtv,
                            snap_p, active_p, sync=True,
                        )
                    )
            fn = self._fn(Bp, R, Wn, fast_iters)
            verdict, nks, nvs, ncnt, nbidx, _conv, ok = fn(
                self._ks, self._vs, self._dev_counts, self._bidx,
                self._lo, self._hi,
                rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                commit_off, self._dev_ok,
            )
            self._ks, self._vs, self._dev_counts, self._bidx = nks, nvs, ncnt, nbidx
            self._dev_ok = ok
            self._counts = None  # unknown until drained
            self._counts_ub = self._counts_ub + 2 * Wn
            self._pipelined_since_check += 1
            self._last_commit = commit_version
            return verdict

        while True:
            pre = (self._ks, self._vs, self._dev_counts, self._bidx, self._counts)
            iters = fast_iters
            while True:
                fn = self._fn(Bp, R, Wn, iters)
                verdict, nks, nvs, ncnt, nbidx, conv, _ok = fn(
                    self._ks, self._vs, self._dev_counts, self._bidx,
                    self._lo, self._hi,
                    rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                    commit_off, self._dev_ok,
                )
                if bool(np.asarray(conv)):
                    break
                # a word0-prefix bucket deeper than 2**iters on some
                # partition: replay at full depth (kernel is pure)
                self.search_fallbacks += 1
                iters = _levels(self._cap) + 1
            counts = np.asarray(ncnt).astype(np.int64)
            if counts.max() <= self._cap:
                self._ks, self._vs, self._bidx = nks, nvs, nbidx
                self._counts = counts
                self._counts_ub = counts.copy()
                self._dev_counts = ncnt
                self._last_commit = commit_version
                break
            # partition overflow: regrow from the pre-batch state (valid:
            # the kernel does not donate its inputs) and replay
            self.regrows += 1
            new_cap = self._cap
            while new_cap < counts.max():
                new_cap *= 2
            self._init_state(
                new_cap, np.asarray(pre[0]), np.asarray(pre[1]),
                pre[4] if pre[4] is not None else np.asarray(pre[2]).astype(np.int64),
            )
        return np.asarray(verdict)

    def check_pipelined(self) -> None:
        """Drain the deferred validity of sync=False resolves (ONE replicated
        device flag + the live counts).  Raises if any batch needed the
        full-depth search fallback or overflowed a partition; the stream must
        then be replayed through sync=True resolves on a fresh instance (the
        kernel is pure, so the host-side batch stream is the source of
        truth)."""
        if self._pipelined_since_check == 0:
            return
        n = self._pipelined_since_check
        self._pipelined_since_check = 0
        if not bool(np.asarray(self._dev_ok)):
            raise RuntimeError(
                f"a pipelined batch among the last {n} failed its deferred"
                " search-convergence/capacity check; replay through sync=True"
            )
        self._counts = np.asarray(self._dev_counts).astype(np.int64)
        self._counts_ub = self._counts.copy()

    def remove_before(self, version: int) -> None:
        if version <= self._oldest:
            return
        self._oldest = version
        off = version - self._base
        if off > 0:
            self._vs = _sharded_gc(self._vs, np.int32(off))
            self._base = version
