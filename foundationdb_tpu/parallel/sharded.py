"""Multi-resolver conflict resolution over a TPU device mesh.

The reference scales OCC by splitting the key space into contiguous
partitions, one per Resolver process: the proxy routes each transaction's
conflict ranges to the resolvers whose partition they intersect
(fdbserver/MasterProxyServer.actor.cpp:280-320 ResolutionRequestBuilder) and
merges the per-resolver verdicts with min() (:558-569).  Crucially each
resolver decides *from its own partition alone* and inserts the write ranges
of transactions it locally judged committed — even if another resolver
aborts that transaction (a deliberate false-positive source the reference
accepts; see Resolver.actor.cpp).  That independence is exactly what makes
the check SPMD:

  mesh axis "resolvers": device i owns key partition [split[i], split[i+1])
  - batch tensors are replicated to all devices (host broadcast — the
    device-side analog of the proxy fanning the batch out over the network)
  - each device clips every range to its partition; ranges that miss the
    partition become padding
  - each device runs the identical single-partition kernel
    (conflict/device.py resolve_core) on its clipped view and local state
  - verdicts merge with lax.pmin over the axis (CONFLICT=0 < TOO_OLD=1 <
    COMMITTED=2, matching the reference enum ConflictSet.h:36-40 — the
    min-combine's load-bearing ordering) — ONE collective per batch,
    riding ICI.

State stays resident per device (the partition's step function), so the
only per-batch transfers are the batch tensors in and B verdicts out.
"""

from __future__ import annotations

import functools
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

if not hasattr(jax, "shard_map"):  # pre-0.4.35 jax: not yet promoted out of
    from jax.experimental.shard_map import shard_map as _exp_shard_map  # experimental

    def _shard_map(f, *args, **kw):
        if "check_vma" in kw:  # the kwarg's pre-promotion name
            kw["check_rep"] = kw.pop("check_vma")
        return _exp_shard_map(f, *args, **kw)

    jax.shard_map = _shard_map

from .. import keys as keymod
from ..conflict import pallas_kernel
from ..conflict.api import ConflictSet, KernelStats, TxInfo, Verdict, validate_batch
from ..conflict.pipeline import PipelinedConflictMixin
from ..conflict.device import (
    _SENT_WORD,
    FAST_SEARCH_ITERS,
    compact_lsm,
    host_bucket_index,
    impl_from_env,
    pack_batch,
    resolve_core,
    resolve_core_inc,
    resolve_core_inc_lsm,
    resolve_core_lsm,
    run_to_step,
)
from ..ops.rmq import build_sparse_table
from ..ops.rmq import _levels
from ..ops.search import lex_less

RESOLVER_AXIS = "resolvers"


def make_resolver_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} resolver devices, only {len(devs)} available")
    return Mesh(np.array(devs[:n]), (RESOLVER_AXIS,))


def _lex_max(a, b):
    """Rowwise lexicographic max of uint32[..., W] keys."""
    return jnp.where(lex_less(a, b)[..., None], b, a)


def _lex_min(a, b):
    return jnp.where(lex_less(b, a)[..., None], b, a)


def _clip_ranges(b, e, tx, lo_row, hi_row):
    """Clip ranges [b, e) to the partition [lo_row, hi_row); ranges that
    miss the partition become sentinel padding with tx = -1 (the device-side
    ResolutionRequestBuilder: only intersecting ranges reach a resolver)."""
    cb = _lex_max(b, lo_row[None, :])
    ce = _lex_min(e, hi_row[None, :])
    live = lex_less(cb, ce) & (tx >= 0)
    sent = jnp.full_like(b, _SENT_WORD)
    return (
        jnp.where(live[:, None], cb, sent),
        jnp.where(live[:, None], ce, sent),
        jnp.where(live, tx, -1),
    )


def _sharded_resolve(
    ks, vs, cnt, bidx,  # per-device state shards: [1, CAP, W], [1, CAP], [1], [1, NB+1]
    lo, hi,  # per-device partition bounds: [1, W] each
    rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off,  # replicated batch
    ok_in,  # replicated bool: validity accumulated across a pipelined stream
    *, cap, n_txn, n_read, n_write, search_iters, merge_impl, search_impl,
):
    ks, vs, lo, hi, bidx = ks[0], vs[0], lo[0], hi[0], bidx[0]
    rb, re_, r_tx = _clip_ranges(rb, re_, r_tx, lo, hi)
    wb, we, w_tx = _clip_ranges(wb, we, w_tx, lo, hi)
    verdict, new_ks, new_vs, new_count, new_bidx, conv, ok = resolve_core(
        ks, vs, bidx, cnt[0], rb, re_, r_tx, wb, we, w_tx, snap, active,
        commit_off, ok_in,
        cap=cap, n_txn=n_txn, n_read=n_read, n_write=n_write,
        search_iters=search_iters, merge_impl=merge_impl,
        search_impl=search_impl,
    )
    # proxy min-combine (MasterProxyServer.actor.cpp:558-569) over ICI; the
    # convergence / stream-validity flags fold the same way (all devices must
    # agree before a batch's verdicts are trusted)
    merged = jax.lax.pmin(verdict, RESOLVER_AXIS)
    all_conv = jax.lax.pmin(conv.astype(jnp.int32), RESOLVER_AXIS) > 0
    all_ok = jax.lax.pmin(ok.astype(jnp.int32), RESOLVER_AXIS) > 0
    return merged, new_ks[None], new_vs[None], new_count[None], new_bidx[None], all_conv, all_ok


def _sharded_resolve_lsm(
    ks, vs, tab, bidx, cnt,            # main level shards
    rks, rvs, rbidx, rcnt,             # recent level shards
    lo, hi,
    rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off,
    ok_in,
    *, cap, rec_cap, n_txn, n_read, n_write, search_iters, rec_iters,
    search_impl, merge_impl,
):
    """LSM twin of _sharded_resolve: per-partition two-level state, the
    same clip → kernel → pmin shape (conflict/device.py resolve_core_lsm)."""
    ks, vs, tab, bidx = ks[0], vs[0], tab[0], bidx[0]
    rks, rvs, rbidx = rks[0], rvs[0], rbidx[0]
    lo, hi = lo[0], hi[0]
    rb, re_, r_tx = _clip_ranges(rb, re_, r_tx, lo, hi)
    wb, we, w_tx = _clip_ranges(wb, we, w_tx, lo, hi)
    verdict, nrks, nrvs, nrbidx, nrcnt, conv, ok = resolve_core_lsm(
        ks, vs, tab, bidx, cnt[0],
        rks, rvs, rbidx, rcnt[0],
        rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off, ok_in,
        cap=cap, rec_cap=rec_cap, n_txn=n_txn, n_read=n_read,
        n_write=n_write, search_iters=search_iters, rec_iters=rec_iters,
        search_impl=search_impl, merge_impl=merge_impl,
    )
    merged = jax.lax.pmin(verdict, RESOLVER_AXIS)
    all_conv = jax.lax.pmin(conv.astype(jnp.int32), RESOLVER_AXIS) > 0
    all_ok = jax.lax.pmin(ok.astype(jnp.int32), RESOLVER_AXIS) > 0
    return (
        merged, nrks[None], nrvs[None], nrbidx[None], nrcnt[None],
        all_conv, all_ok,
    )


def _sharded_resolve_inc(
    ks, vs, cnt, bidx,                 # main level shards (read-only here)
    runs_b, runs_e, runs_ver,          # per-partition run shards
    lo, hi,
    slot, rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off,
    ok_in,
    *, cap, run_cap, n_txn, n_read, n_write, search_iters, search_impl,
    probe_impl, merge_impl,
):
    """Incremental twin of _sharded_resolve: the same clip → kernel → pmin
    shape, with the committed writes appending as a per-partition run
    (conflict/device.py resolve_core_inc — the sort-scan probe runs per
    shard, Pallas or XLA per the capability probe)."""
    ks, vs, bidx = ks[0], vs[0], bidx[0]
    lo, hi = lo[0], hi[0]
    rb, re_, r_tx = _clip_ranges(rb, re_, r_tx, lo, hi)
    wb, we, w_tx = _clip_ranges(wb, we, w_tx, lo, hi)
    verdict, nb, ne, nv, conv, ok = resolve_core_inc(
        ks, vs, bidx, cnt[0],
        runs_b[0], runs_e[0], runs_ver[0], slot,
        rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off, ok_in,
        cap=cap, run_cap=run_cap, n_txn=n_txn, n_read=n_read,
        n_write=n_write, search_iters=search_iters,
        search_impl=search_impl, probe_impl=probe_impl,
        merge_impl=merge_impl,
    )
    merged = jax.lax.pmin(verdict, RESOLVER_AXIS)
    all_conv = jax.lax.pmin(conv.astype(jnp.int32), RESOLVER_AXIS) > 0
    all_ok = jax.lax.pmin(ok.astype(jnp.int32), RESOLVER_AXIS) > 0
    return merged, nb[None], ne[None], nv[None], all_conv, all_ok


def _sharded_resolve_inc_lsm(
    ks, tab, cnt, bidx,
    runs_b, runs_e, runs_ver,
    lo, hi,
    slot, rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off,
    ok_in,
    *, cap, run_cap, n_txn, n_read, n_write, search_iters, search_impl,
    probe_impl, merge_impl,
):
    """LSM twin: main history from the cached per-partition sparse table."""
    ks, tab, bidx = ks[0], tab[0], bidx[0]
    lo, hi = lo[0], hi[0]
    rb, re_, r_tx = _clip_ranges(rb, re_, r_tx, lo, hi)
    wb, we, w_tx = _clip_ranges(wb, we, w_tx, lo, hi)
    verdict, nb, ne, nv, conv, ok = resolve_core_inc_lsm(
        ks, tab, bidx, cnt[0],
        runs_b[0], runs_e[0], runs_ver[0], slot,
        rb, re_, r_tx, wb, we, w_tx, snap, active, commit_off, ok_in,
        cap=cap, run_cap=run_cap, n_txn=n_txn, n_read=n_read,
        n_write=n_write, search_iters=search_iters,
        search_impl=search_impl, probe_impl=probe_impl,
        merge_impl=merge_impl,
    )
    merged = jax.lax.pmin(verdict, RESOLVER_AXIS)
    all_conv = jax.lax.pmin(conv.astype(jnp.int32), RESOLVER_AXIS) > 0
    all_ok = jax.lax.pmin(ok.astype(jnp.int32), RESOLVER_AXIS) > 0
    return merged, nb[None], ne[None], nv[None], all_conv, all_ok


def build_sharded_resolver_inc(
    mesh: Mesh, *, cap: int, run_cap: int, n_txn: int, n_read: int,
    n_write: int, search_iters: int, search_impl: str, probe_impl: str,
    lsm: bool, merge_impl: str | None = None,
):
    shard = P(RESOLVER_AXIS)
    repl = P()
    merge_impl = impl_from_env("merge", merge_impl)
    fn = jax.shard_map(
        functools.partial(
            _sharded_resolve_inc_lsm if lsm else _sharded_resolve_inc,
            cap=cap, run_cap=run_cap, n_txn=n_txn, n_read=n_read,
            n_write=n_write, search_iters=search_iters,
            search_impl=search_impl, probe_impl=probe_impl,
            merge_impl=merge_impl,
        ),
        mesh=mesh,
        in_specs=(shard,) * 7 + (shard, shard) + (repl,) * 11,
        out_specs=(repl, shard, shard, shard, repl, repl),
        check_vma=False,
    )
    return jax.jit(fn)


def _sharded_compact_runs(ks, vs, runs_b, runs_e, runs_ver, *, cap, slots,
                          merge_impl):
    """Fold ALL run slots into each partition's main level (empty slots are
    sentinel runs at version 0 — a no-op fold), returning the per-partition
    fold-count maximum so the host can detect overflow and regrow.  One
    compiled shape regardless of how many slots are live."""
    k, v = ks[0], vs[0]
    maxcnt = jnp.int32(0)
    for s in range(slots):
        rows, vals = run_to_step(runs_b[0, s], runs_e[0, s], runs_ver[0, s])
        k, v, cnt, bidx, tab = compact_lsm(
            k, v, rows, vals, cap=cap, merge_impl=merge_impl
        )
        maxcnt = jnp.maximum(maxcnt, cnt)
    return k[None], v[None], cnt[None], bidx[None], tab[None], maxcnt[None]


def build_sharded_run_compactor(mesh: Mesh, *, cap: int, slots: int,
                                merge_impl: str | None = None):
    shard = P(RESOLVER_AXIS)
    merge_impl = impl_from_env("merge", merge_impl)
    fn = jax.shard_map(
        functools.partial(_sharded_compact_runs, cap=cap, slots=slots,
                          merge_impl=merge_impl),
        mesh=mesh,
        in_specs=(shard,) * 5,
        out_specs=(shard,) * 6,
        check_vma=False,
    )
    return jax.jit(fn)


def _sharded_compact(ks, vs, rks, rvs, *, cap, merge_impl):
    """Per-partition compact_lsm under shard_map (every partition folds its
    recent level at once — the host triggers when any is near full)."""
    nks, nvs, ncnt, nbidx, ntab = compact_lsm(
        ks[0], vs[0], rks[0], rvs[0], cap=cap, merge_impl=merge_impl
    )
    return nks[None], nvs[None], ncnt[None], nbidx[None], ntab[None]


def build_sharded_resolver_lsm(
    mesh: Mesh, *, cap: int, rec_cap: int, n_txn: int, n_read: int,
    n_write: int, search_iters: int, rec_iters: int,
    search_impl: str, merge_impl: str,
):
    shard = P(RESOLVER_AXIS)
    repl = P()
    fn = jax.shard_map(
        functools.partial(
            _sharded_resolve_lsm, cap=cap, rec_cap=rec_cap, n_txn=n_txn,
            n_read=n_read, n_write=n_write, search_iters=search_iters,
            rec_iters=rec_iters, search_impl=search_impl,
            merge_impl=merge_impl,
        ),
        mesh=mesh,
        in_specs=(shard,) * 9 + (shard, shard) + (repl,) * 10,
        out_specs=(repl, shard, shard, shard, shard, repl, repl),
        check_vma=False,
    )
    return jax.jit(fn)


def build_sharded_compactor(mesh: Mesh, *, cap: int,
                            merge_impl: str | None = None):
    shard = P(RESOLVER_AXIS)
    merge_impl = impl_from_env("merge", merge_impl)
    fn = jax.shard_map(
        functools.partial(_sharded_compact, cap=cap, merge_impl=merge_impl),
        mesh=mesh,
        in_specs=(shard,) * 4,
        out_specs=(shard,) * 5,
        check_vma=False,
    )
    return jax.jit(fn)


@jax.jit
def _sharded_gc(vs, off):
    """remove_before on the sharded gap-version array: elementwise rebase,
    so the output inherits the input's sharding — compiled once, offset is
    a runtime argument (same pattern as conflict/device.py _gc_kernel)."""
    return jnp.maximum(vs - off, 0)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _sharded_gc_lsm(vs, tab, rec_vs, off):
    """Fused, donating GC for the LSM levels (the _gc_lsm_kernel twin): one
    dispatch, in-place — tab is the largest array in the system and must
    not be transiently doubled."""
    return (
        jnp.maximum(vs - off, 0),
        jnp.maximum(tab - off, 0),
        jnp.maximum(rec_vs - off, 0),
    )


def build_sharded_resolver(
    mesh: Mesh, *, cap: int, n_txn: int, n_read: int, n_write: int,
    search_iters: int, merge_impl: str | None = None,
    search_impl: str | None = None,
):
    """Jit-compiled sharded resolve step for fixed bucket sizes."""
    merge_impl = impl_from_env("merge", merge_impl)
    search_impl = impl_from_env("search", search_impl)
    shard = P(RESOLVER_AXIS)
    repl = P()
    fn = jax.shard_map(
        functools.partial(
            _sharded_resolve, cap=cap, n_txn=n_txn, n_read=n_read,
            n_write=n_write, search_iters=search_iters, merge_impl=merge_impl,
            search_impl=search_impl,
        ),
        mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard, shard) + (repl,) * 10,
        out_specs=(repl, shard, shard, shard, shard, repl, repl),
        # the kernel's loop carries start replicated and become varying;
        # skip the static replication check rather than pcast every carry
        check_vma=False,
    )
    return jax.jit(fn)


class ShardedDeviceConflictSet(PipelinedConflictMixin, ConflictSet):
    """Key-partitioned ConflictSet over an N-device mesh.

    Equivalent to N reference Resolvers plus the proxy's verdict merge, with
    the partition split points fixed at construction (the reference
    rebalances online via masterserver.actor.cpp:964 resolutionBalancing;
    here rebalancing = build a new instance with new splits — resolver state
    evaporates on generation change anyway, SURVEY §5 failure detection).

    Shares the single-device set's input pipeline (conflict/pipeline.py):
    ONE bulk pack per batch feeds every shard (the batch is replicated; the
    kernel clips per partition), and resolve_deferred gives the split-phase
    dispatch with the same snapshot/replay recovery.
    """

    _PIPELINE_SNAPSHOT_ATTRS = (
        "_ks", "_vs", "_bidx", "_counts", "_counts_ub", "_dev_counts",
        "_dev_ok", "_pipelined_since_check", "_last_commit", "_base",
        "_oldest", "_cap", "_tab", "_rec_ks", "_rec_vs", "_rec_bidx",
        "_rec_dev_counts", "_rec_counts_ub", "_rec_cap",
        "_runs_b", "_runs_e", "_runs_ver", "_n_runs", "_run_cap",
    )

    def __init__(
        self,
        mesh: Mesh,
        split_keys: Sequence[bytes],
        oldest_version: int = 0,
        *,
        max_key_bytes: int = keymod.DEFAULT_MAX_KEY_BYTES,
        capacity: int = 1 << 14,
        merge_impl: str | None = None,
        search_impl: str | None = None,
        lsm: bool | None = None,         # None: FDBTPU_LSM env ("1") or False
        recent_capacity: int = 1 << 12,  # LSM recent level per partition
        incremental: bool | None = None,  # None: FDBTPU_INCREMENTAL env, on
        run_slots: int = 8,              # K: per-partition run slots
        run_capacity: int = 1 << 10,     # per-run interval capacity
        pallas: str | None = None,       # probe override: auto|tpu|interpret|off
    ) -> None:
        self._merge_impl = impl_from_env("merge", merge_impl)
        self._search_impl = impl_from_env("search", search_impl)
        import os

        self._lsm = (
            os.environ.get("FDBTPU_LSM", "") == "1" if lsm is None else lsm
        )
        self._incremental = (
            os.environ.get("FDBTPU_INCREMENTAL", "1") == "1"
            if incremental is None
            else incremental
        )
        self._probe_impl = pallas_kernel.pallas_mode(pallas) or "xla"
        self._K = run_slots
        self._run_cap = run_capacity
        from ..conflict.device import _rec_search_iters

        self._rec_iters = _rec_search_iters()
        self._rec_cap = recent_capacity
        self.compactions = 0
        n = mesh.devices.size
        if len(split_keys) != n - 1:
            raise ValueError(f"need {n - 1} split keys for {n} resolver devices")
        if list(split_keys) != sorted(split_keys) or len(set(split_keys)) != len(split_keys):
            raise ValueError("split keys must be strictly increasing")
        self._mesh = mesh
        self._n = n
        self._max_key_bytes = max_key_bytes
        self._W = keymod.num_words(max_key_bytes)
        self._base = oldest_version
        self._oldest = oldest_version
        self._last_commit = oldest_version
        self._fns: dict[tuple[int, int, int, int, int], object] = {}
        self.search_fallbacks = 0
        self.regrows = 0
        self.stats = KernelStats(backend="sharded-device")
        self.stats.merge_impl = self._merge_impl
        self._pipeline_init()  # staging arenas + deferred-resolve window

        bounds = [b""] + list(split_keys)
        lo = keymod.encode_keys(bounds, max_key_bytes)
        hi = np.empty_like(lo)
        hi[:-1] = lo[1:]
        hi[-1] = keymod.sentinel(max_key_bytes)
        self._state_sharding = NamedSharding(mesh, P(RESOLVER_AXIS))
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        self._lo, self._hi = dev(lo), dev(hi)
        self._np_lo = lo
        self._init_state(capacity)

    def _init_state(self, capacity: int, ks=None, vs=None, counts=None) -> None:
        """Fresh (or regrown) per-partition state arrays."""
        n, W = self._n, self._W
        nks = np.full((n, capacity, W), _SENT_WORD, dtype=np.uint32)
        nvs = np.zeros((n, capacity), dtype=np.int32)
        if ks is None:
            nks[:, 0, :] = self._np_lo  # each partition starts at its own floor
            counts = np.ones(n, dtype=np.int64)
        else:
            c = min(ks.shape[1], capacity)
            nks[:, :c] = np.asarray(ks)[:, :c]
            nvs[:, :c] = np.asarray(vs)[:, :c]
        self._cap = capacity
        self._fns = {}  # cap is a static arg of the compiled step
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        self._ks, self._vs = dev(nks), dev(nvs)
        self._counts = np.asarray(counts, dtype=np.int64)
        self._counts_ub = self._counts.copy()
        self._dev_counts = dev(self._counts.astype(np.int32))
        if not hasattr(self, "_dev_ok"):
            # fresh construction only: a regrow must not reset the pipelined
            # validity accumulator (same contract as DeviceConflictSet)
            self._dev_ok = jax.device_put(
                np.asarray(True), NamedSharding(self._mesh, P())
            )
            self._pipelined_since_check = 0
        # word0-prefix bucket index per partition (sentinels -> last bucket)
        bidx = np.stack([host_bucket_index(nks[i]) for i in range(n)])
        self._bidx = dev(bidx)
        if self._lsm:
            # cached per-partition main sparse table + a fresh recent level
            self._tab = jax.jit(
                jax.vmap(lambda v: build_sparse_table(v, jnp.maximum, 0)),
                out_shardings=self._state_sharding,
            )(self._vs)
            self._init_recent()
        if self._incremental and not hasattr(self, "_runs_b"):
            # fresh construction only — regrows keep uncompacted runs
            self._init_runs(self._run_cap)

    def _init_runs(self, run_cap: int) -> None:
        from ..conflict.device import _bucket

        n, W = self._n, self._W
        run_cap = _bucket(run_cap)  # kernel stride math wants a power of two
        self._run_cap = run_cap
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        shape = (n, self._K, run_cap, W)
        self._runs_b = dev(np.full(shape, _SENT_WORD, dtype=np.uint32))
        self._runs_e = dev(np.full(shape, _SENT_WORD, dtype=np.uint32))
        self._runs_ver = dev(np.zeros((n, self._K), dtype=np.int32))
        self._n_runs = 0

    def _grow_runs(self, new_cap: int) -> None:
        n, K, W = self._n, self._K, self._W
        b = np.asarray(self._runs_b)
        e = np.asarray(self._runs_e)
        old = b.shape[2]
        nb = np.full((n, K, new_cap, W), _SENT_WORD, dtype=np.uint32)
        ne = np.full((n, K, new_cap, W), _SENT_WORD, dtype=np.uint32)
        nb[:, :, :old] = b
        ne[:, :, :old] = e
        ver = self._runs_ver
        self._run_cap = new_cap
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        self._runs_b, self._runs_e = dev(nb), dev(ne)
        self._runs_ver = ver

    def _init_recent(self) -> None:
        n, W, rec_cap = self._n, self._W, self._rec_cap
        rk = np.full((n, rec_cap, W), _SENT_WORD, dtype=np.uint32)
        rk[:, 0, :] = self._np_lo  # each partition's floor row
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        self._rec_ks = dev(rk)
        self._rec_vs = dev(np.zeros((n, rec_cap), dtype=np.int32))
        self._rec_bidx = dev(
            np.stack([host_bucket_index(rk[i]) for i in range(n)])
        )
        self._rec_counts_ub = np.ones(self._n, dtype=np.int64)
        self._rec_dev_counts = dev(np.ones(n, dtype=np.int32))

    def _grow_recent(self, new_rec_cap: int) -> None:
        """Sentinel-pad the recent level in place — no fold, no main-level
        work (the single-device twin's _grow_recent)."""
        n, W = self._n, self._W
        rk = np.asarray(self._rec_ks)
        rv = np.asarray(self._rec_vs)
        nks = np.full((n, new_rec_cap, W), _SENT_WORD, dtype=np.uint32)
        nks[:, : rk.shape[1]] = rk
        nvs = np.zeros((n, new_rec_cap), dtype=np.int32)
        nvs[:, : rv.shape[1]] = rv
        counts, ub = self._rec_dev_counts, self._rec_counts_ub
        self._rec_cap = new_rec_cap
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        self._rec_ks, self._rec_vs = dev(nks), dev(nvs)
        self._rec_bidx = dev(
            np.stack([host_bucket_index(nks[i]) for i in range(n)])
        )
        self._rec_dev_counts = counts
        self._rec_counts_ub = ub

    def _compact(self) -> None:
        """Fold every partition's recent level into its main level; regrow
        main if any partition's union no longer fits."""
        t0 = time.perf_counter()
        while True:
            key = ("compact", self._cap, self._rec_cap, self._merge_impl)
            if key not in self._fns:
                self._fns[key] = build_sharded_compactor(
                    self._mesh, cap=self._cap, merge_impl=self._merge_impl
                )
            nks, nvs, ncnt, nbidx, ntab = self._fns[key](
                self._ks, self._vs, self._rec_ks, self._rec_vs
            )
            counts = np.asarray(ncnt).astype(np.int64)
            if counts.max() <= self._cap:
                break
            self.regrows += 1
            new_cap = self._cap
            while new_cap < counts.max():
                new_cap *= 2
            self._grow_main(new_cap)
        self._ks, self._vs, self._bidx, self._tab = nks, nvs, nbidx, ntab
        self._counts = counts
        self._counts_ub = counts.copy()
        self._dev_counts = ncnt
        self._init_recent()
        self.compactions += 1
        dt = time.perf_counter() - t0
        self.stats.merge_s += dt
        self.stats.fold_wall_s[self._merge_impl] = (
            self.stats.fold_wall_s.get(self._merge_impl, 0.0) + dt
        )

    def _grow_main(self, new_cap: int) -> None:
        """Pad main to new_cap (compaction retry).  The caller's compactor
        rebuilds bidx/tab from the folded result, so only ks/vs grow here."""
        n, W = self._n, self._W
        ks = np.asarray(self._ks)
        vs = np.asarray(self._vs)
        nks = np.full((n, new_cap, W), _SENT_WORD, dtype=np.uint32)
        nks[:, : ks.shape[1]] = ks
        nvs = np.zeros((n, new_cap), dtype=np.int32)
        nvs[:, : vs.shape[1]] = vs
        self._cap = new_cap
        self._fns = {}
        dev = functools.partial(jax.device_put, device=self._state_sharding)
        self._ks, self._vs = dev(nks), dev(nvs)

    @property
    def oldest_version(self) -> int:
        return self._oldest

    def _offset(self, version: int) -> int:
        off = version - self._base
        if off >= 2**31 - 2**24:
            raise OverflowError("version offset overflow; call remove_before")
        return max(off, 0)

    def _offset_array(self, versions: np.ndarray) -> np.ndarray:
        """Vectorized _offset twin for the bulk packer."""
        off = np.asarray(versions, dtype=np.int64) - self._base
        if off.size and int(off.max()) >= 2**31 - 2**24:
            raise OverflowError("version offset overflow; call remove_before")
        return np.maximum(off, 0)

    def _fn(self, n_txn: int, n_read: int, n_write: int, search_iters: int):
        key = (
            self._cap, n_txn, n_read, n_write, search_iters,
            self._merge_impl, self._search_impl,
        )
        if key not in self._fns:
            self._fns[key] = build_sharded_resolver(
                self._mesh, cap=self._cap, n_txn=n_txn, n_read=n_read,
                n_write=n_write, search_iters=search_iters,
                merge_impl=self._merge_impl, search_impl=self._search_impl,
            )
        return self._fns[key]

    def _fn_lsm(self, n_txn: int, n_read: int, n_write: int,
                search_iters: int, rec_iters: int):
        key = (
            "lsm", self._cap, self._rec_cap, n_txn, n_read, n_write,
            search_iters, rec_iters, self._merge_impl, self._search_impl,
        )
        if key not in self._fns:
            self._fns[key] = build_sharded_resolver_lsm(
                self._mesh, cap=self._cap, rec_cap=self._rec_cap,
                n_txn=n_txn, n_read=n_read, n_write=n_write,
                search_iters=search_iters, rec_iters=rec_iters,
                search_impl=self._search_impl, merge_impl=self._merge_impl,
            )
        return self._fns[key]

    def _fn_inc(self, n_txn: int, n_read: int, n_write: int, search_iters: int):
        key = (
            "inc", self._lsm, self._cap, self._run_cap, n_txn, n_read,
            n_write, search_iters, self._search_impl, self._probe_impl,
            self._merge_impl,
        )
        if key not in self._fns:
            self._fns[key] = build_sharded_resolver_inc(
                self._mesh, cap=self._cap, run_cap=self._run_cap,
                n_txn=n_txn, n_read=n_read, n_write=n_write,
                search_iters=search_iters, search_impl=self._search_impl,
                probe_impl=self._probe_impl, lsm=self._lsm,
                merge_impl=self._merge_impl,
            )
        return self._fns[key]

    @property
    def capacity(self) -> int:
        return self._cap

    def healthcheck(self) -> bool:
        """One tiny host<->device round trip through every shard's count
        lane: raises (classified by the DeviceSupervisor) when a mesh
        device is gone or the stream is poisoned.  Forces a stream sync —
        supervisor probes only, never the hot path."""
        return int(np.asarray(self._dev_counts).sum()) >= 0

    def resolve_batch(self, commit_version: int, txns: Sequence[TxInfo]) -> list[Verdict]:
        self._drain_all()  # settle any deferred window before sync work
        validate_batch(commit_version, txns, self._oldest)
        B = len(txns)
        if B == 0:
            if commit_version <= self._last_commit:
                raise ValueError(
                    f"commit_version {commit_version} not after last batch "
                    f"{self._last_commit}"
                )
            self._last_commit = commit_version
            return []
        t_pack = time.perf_counter()
        rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, Bp = pack_batch(
            txns, self._oldest, self._offset, self._max_key_bytes,
            arena=self._arena, stats=self.stats,
            offset_array=self._offset_array,
        )
        self.stats.pack_s += time.perf_counter() - t_pack
        codes = self.resolve_arrays(
            commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p
        )
        return [Verdict(int(c)) for c in codes[:B]]

    def resolve_arrays(
        self, commit_version: int, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
        sync: bool = True,
    ):
        """Packed fast path, mirroring DeviceConflictSet.resolve_arrays.

        sync=True: fetch verdicts; handle fast-search fallback (full-depth
        replay) and capacity regrow inline.

        sync=False: PIPELINED — dispatch and return the device verdict array
        without waiting; deferred convergence/capacity validity folds into a
        replicated device flag drained by check_pipelined()."""
        if sync and self._inflight:
            # mixed use: settle the deferred window first (see device.py)
            self._drain_all()
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit_version {commit_version} not after last batch {self._last_commit}"
            )
        Bp, R, Wn = snap_p.shape[0], rbv.shape[0], wbv.shape[0]
        commit_off = np.int32(self._offset(commit_version))
        fast_iters = min(FAST_SEARCH_ITERS, _levels(self._cap) + 1)

        if self._incremental:
            return self._resolve_arrays_inc(
                commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p,
                active_p, sync, Bp, R, Wn, commit_off, fast_iters,
            )

        if self._lsm:
            return self._resolve_arrays_lsm(
                commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p,
                active_p, sync, Bp, R, Wn, commit_off,
            )

        if not sync:
            # a batch adds at most 2*Wn boundaries per partition; if the
            # host-tracked upper bound could overflow, drain the pipeline —
            # and if genuinely near capacity, go through sync (which regrows)
            if self._counts_ub.max() + 2 * Wn > self._cap:
                self.check_pipelined()
                if self._counts_ub.max() + 2 * Wn > self._cap:
                    return np.asarray(
                        self.resolve_arrays(
                            commit_version, rbv, rev, rtv, wbv, wev, wtv,
                            snap_p, active_p, sync=True,
                        )
                    )
            fn = self._fn(Bp, R, Wn, fast_iters)
            verdict, nks, nvs, ncnt, nbidx, _conv, ok = fn(
                self._ks, self._vs, self._dev_counts, self._bidx,
                self._lo, self._hi,
                rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                commit_off, self._dev_ok,
            )
            self._ks, self._vs, self._dev_counts, self._bidx = nks, nvs, ncnt, nbidx
            self._dev_ok = ok
            self._counts = None  # unknown until drained
            self._counts_ub = self._counts_ub + 2 * Wn
            self._pipelined_since_check += 1
            self._last_commit = commit_version
            return verdict

        while True:
            pre = (self._ks, self._vs, self._dev_counts, self._bidx, self._counts)
            iters = fast_iters
            while True:
                fn = self._fn(Bp, R, Wn, iters)
                verdict, nks, nvs, ncnt, nbidx, conv, _ok = fn(
                    self._ks, self._vs, self._dev_counts, self._bidx,
                    self._lo, self._hi,
                    rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                    commit_off, self._dev_ok,
                )
                if bool(np.asarray(conv)):
                    break
                # a word0-prefix bucket deeper than 2**iters on some
                # partition: replay at full depth (kernel is pure)
                self.search_fallbacks += 1
                iters = _levels(self._cap) + 1
            counts = np.asarray(ncnt).astype(np.int64)
            if counts.max() <= self._cap:
                self._ks, self._vs, self._bidx = nks, nvs, nbidx
                self._counts = counts
                self._counts_ub = counts.copy()
                self._dev_counts = ncnt
                self._last_commit = commit_version
                break
            # partition overflow: regrow from the pre-batch state (valid:
            # the kernel does not donate its inputs) and replay
            self.regrows += 1
            new_cap = self._cap
            while new_cap < counts.max():
                new_cap *= 2
            self._init_state(
                new_cap, np.asarray(pre[0]), np.asarray(pre[1]),
                pre[4] if pre[4] is not None else np.asarray(pre[2]).astype(np.int64),
            )
        return np.asarray(verdict)

    def _resolve_arrays_inc(
        self, commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
        sync, Bp, R, Wn, commit_off, fast_iters,
    ):
        """Incremental sharded resolve: each partition appends its clipped
        committed union as a run; the deferred fold fires host-side when the
        K slots fill.  Run bookkeeping is host-deterministic (appends cannot
        overflow: run_cap >= 2*Wn by construction), so pipelined mode
        defers only search convergence — mirroring DeviceConflictSet."""
        from ..conflict.device import _bucket

        if 2 * Wn > self._run_cap:
            self._grow_runs(_bucket(2 * Wn))
        if self._n_runs >= self._K:
            self._compact_runs()
        slot = jnp.int32(self._n_runs)
        main = (
            (self._ks, self._tab) if self._lsm else (self._ks, self._vs)
        )

        if not sync:
            fn = self._fn_inc(Bp, R, Wn, fast_iters)
            verdict, nb, ne, nv, _conv, ok = fn(
                main[0], main[1], self._dev_counts, self._bidx,
                self._runs_b, self._runs_e, self._runs_ver,
                self._lo, self._hi,
                slot, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                commit_off, self._dev_ok,
            )
            self._runs_b, self._runs_e, self._runs_ver = nb, ne, nv
            self._dev_ok = ok
            self._n_runs += 1
            self._pipelined_since_check += 1
            self._last_commit = commit_version
            return verdict

        iters = fast_iters
        while True:
            fn = self._fn_inc(Bp, R, Wn, iters)
            verdict, nb, ne, nv, conv, _ok = fn(
                main[0], main[1], self._dev_counts, self._bidx,
                self._runs_b, self._runs_e, self._runs_ver,
                self._lo, self._hi,
                slot, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                commit_off, self._dev_ok,
            )
            if bool(np.asarray(conv)):
                break
            self.search_fallbacks += 1
            iters = _levels(self._cap) + 1
        self._runs_b, self._runs_e, self._runs_ver = nb, ne, nv
        self._n_runs += 1
        self._last_commit = commit_version
        return np.asarray(verdict)

    def _compact_runs(self) -> None:
        """The deferred k-way merge, per partition under shard_map: fold all
        K slots into main (empty slots fold as no-ops — one compiled shape),
        regrowing main when any partition's union outgrows it."""
        if self._n_runs == 0:
            return
        t0 = time.perf_counter()
        while True:
            key = ("compact_runs", self._cap, self._run_cap, self._K,
                   self._merge_impl)
            if key not in self._fns:
                self._fns[key] = build_sharded_run_compactor(
                    self._mesh, cap=self._cap, slots=self._K,
                    merge_impl=self._merge_impl,
                )
            nks, nvs, ncnt, nbidx, ntab, maxcnt = self._fns[key](
                self._ks, self._vs, self._runs_b, self._runs_e, self._runs_ver
            )
            worst = int(np.asarray(maxcnt).max())
            if worst <= self._cap:
                break
            self.regrows += 1
            new_cap = self._cap
            while new_cap < worst:
                new_cap *= 2
            self._grow_main(new_cap)
        self._ks, self._vs, self._bidx = nks, nvs, nbidx
        if self._lsm:
            self._tab = ntab
        counts = np.asarray(ncnt).astype(np.int64)
        self._counts = counts
        self._counts_ub = counts.copy()
        self._dev_counts = ncnt
        self._init_runs(self._run_cap)
        self.compactions += 1
        dt = time.perf_counter() - t0
        self.stats.compact_s += dt
        self.stats.merge_s += dt
        self.stats.fold_wall_s[self._merge_impl] = (
            self.stats.fold_wall_s.get(self._merge_impl, 0.0) + dt
        )

    def _resolve_arrays_lsm(
        self, commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
        sync, Bp, R, Wn, commit_off,
    ):
        from ..conflict.device import _bucket

        if 2 * Wn + 1 > self._rec_cap:
            # a single batch larger than the recent level: pad recent in
            # place (power-of-two, so jit cache keys stay bounded — the
            # single-device _grow_recent contract)
            self._grow_recent(_bucket(4 * Wn + 2))
        if self._rec_counts_ub.max() + 2 * Wn > self._rec_cap:
            # conservative ub: drain the exact counts first — clipping +
            # coalescing usually keep the real counts far below it
            self.check_pipelined()
            if self._rec_counts_ub.max() + 2 * Wn > self._rec_cap:
                self._compact()
        fast_iters = min(FAST_SEARCH_ITERS, _levels(self._cap) + 1)
        # FDBTPU_REC_ITERS applies here too (device/sharded knob parity;
        # read once at construction, like DeviceConflictSet)
        rec_iters = min(self._rec_iters, _levels(self._rec_cap) + 1)

        if not sync:
            fn = self._fn_lsm(Bp, R, Wn, fast_iters, rec_iters)
            verdict, nrks, nrvs, nrbidx, nrcnt, _conv, ok = fn(
                self._ks, self._vs, self._tab, self._bidx, self._dev_counts,
                self._rec_ks, self._rec_vs, self._rec_bidx, self._rec_dev_counts,
                self._lo, self._hi,
                rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                commit_off, self._dev_ok,
            )
            self._rec_ks, self._rec_vs = nrks, nrvs
            self._rec_bidx, self._rec_dev_counts = nrbidx, nrcnt
            self._dev_ok = ok
            self._rec_counts_ub = self._rec_counts_ub + 2 * Wn
            self._pipelined_since_check += 1
            self._last_commit = commit_version
            return verdict

        iters, riters = fast_iters, rec_iters
        while True:
            fn = self._fn_lsm(Bp, R, Wn, iters, riters)
            verdict, nrks, nrvs, nrbidx, nrcnt, conv, _ok = fn(
                self._ks, self._vs, self._tab, self._bidx, self._dev_counts,
                self._rec_ks, self._rec_vs, self._rec_bidx, self._rec_dev_counts,
                self._lo, self._hi,
                rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p,
                commit_off, self._dev_ok,
            )
            if bool(np.asarray(conv)):
                break
            self.search_fallbacks += 1
            iters = _levels(self._cap) + 1
            riters = _levels(self._rec_cap) + 1
        rcounts = np.asarray(nrcnt).astype(np.int64)
        if rcounts.max() > self._rec_cap:
            # coalescing estimate beaten: compact (pre-batch recent intact —
            # the kernel does not donate) and replay this batch
            self._compact()
            return self._resolve_arrays_lsm(
                commit_version, rbv, rev, rtv, wbv, wev, wtv, snap_p,
                active_p, sync, Bp, R, Wn, commit_off,
            )
        self._rec_ks, self._rec_vs = nrks, nrvs
        self._rec_bidx, self._rec_dev_counts = nrbidx, nrcnt
        self._rec_counts_ub = rcounts.copy()
        self._last_commit = commit_version
        return np.asarray(verdict)

    def check_pipelined(self) -> None:
        """Drain the deferred validity of sync=False resolves (ONE replicated
        device flag + the live counts).  Raises if any batch needed the
        full-depth search fallback or overflowed a partition; the stream must
        then be replayed through sync=True resolves on a fresh instance (the
        kernel is pure, so the host-side batch stream is the source of
        truth)."""
        if self._pipelined_since_check == 0:
            return
        n = self._pipelined_since_check
        self._pipelined_since_check = 0
        if not bool(np.asarray(self._dev_ok)):
            raise RuntimeError(
                f"a pipelined batch among the last {n} failed its deferred"
                " search-convergence/capacity check; replay through sync=True"
            )
        if self._lsm:
            self._rec_counts_ub = np.asarray(self._rec_dev_counts).astype(np.int64)
        else:
            self._counts = np.asarray(self._dev_counts).astype(np.int64)
            self._counts_ub = self._counts.copy()

    def remove_before(self, version: int) -> None:
        if version <= self._oldest:
            return
        self._oldest = version
        off = version - self._base
        if off > 0:
            if self._lsm:
                if self._inflight:
                    # a deferred window is open: the recovery snapshot may
                    # alias these buffers — clamp WITHOUT donation
                    o = np.int32(off)
                    self._vs = _sharded_gc(self._vs, o)
                    self._tab = _sharded_gc(self._tab, o)
                    self._rec_vs = _sharded_gc(self._rec_vs, o)
                else:
                    # range-max commutes with the monotone clamp: the cached
                    # tables clamp in place, like the single-device set
                    self._vs, self._tab, self._rec_vs = _sharded_gc_lsm(
                        self._vs, self._tab, self._rec_vs, np.int32(off)
                    )
            else:
                self._vs = _sharded_gc(self._vs, np.int32(off))
            if self._incremental:
                # dead runs clamp to version 0 and never conflict again
                # (elementwise, so the output keeps the input's sharding)
                self._runs_ver = _sharded_gc(self._runs_ver, np.int32(off))
            self._base = version
            self._note_pipeline_gc(version)
