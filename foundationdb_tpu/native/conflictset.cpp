// Native CPU ConflictSet: a skip-list step function over byte-string key space.
//
// This is the CPU baseline the TPU kernel is measured against, covering the
// reference's SkipList-based ConflictSet (fdbserver/SkipList.cpp, semantics
// at fdbserver/ConflictSet.h:27-60): batched OCC over key ranges with an MVCC
// version window.  Written fresh for this framework: the committed-write
// history is a step function (sorted boundary keys; each node's value is the
// version of the gap [node.key, next.key)) — the same mathematical object the
// device kernel keeps as tensors — stored in a skip list:
//   read check   QueryMax(b, e): O(log n) descent + walk over the gaps the
//                range actually covers (short ranges cover 1-2 gaps)
//   write insert Assign(b, e, v): O(log n + interior boundaries removed)
//   GC           ClampBelow(v):   amortized, driven by remove_before
// Exposed as a C ABI loaded via ctypes behind the plugin seam
// (conflict/plugin.py; pattern: fdbrpc/LoadPlugin.h:30-44).
//
// Determinism: tower heights come from a private xorshift64 RNG seeded at
// construction, and verdicts are height-independent, so the abort set is a
// pure function of the batch stream.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <utility>
#include <vector>

namespace {

using Key = std::string;  // byte strings; std::string order == memcmp order

constexpr int kMaxLevel = 26;

struct Node {
  Key key;              // boundary: this node's gap is [key, next[0]->key)
  int64_t gap_version;  // version of that gap (0 = never written / GC'd)
  int level;            // tower height, 1..kMaxLevel
  Node* next[1];        // flexible tower: next[0..level-1]

  static Node* make(const Key& k, int64_t v, int level) {
    Node* n = static_cast<Node*>(
        std::malloc(sizeof(Node) + (level - 1) * sizeof(Node*)));
    new (&n->key) Key(k);
    n->gap_version = v;
    n->level = level;
    std::memset(n->next, 0, level * sizeof(Node*));
    return n;
  }
  static void destroy(Node* n) {
    n->key.~Key();
    std::free(n);
  }
};

class SkipListStepFunction {
 public:
  explicit SkipListStepFunction(uint64_t seed) : rng_(seed | 1) {
    head_ = Node::make(Key(), 0, kMaxLevel);  // "" boundary, version 0
  }
  ~SkipListStepFunction() {
    Node* n = head_;
    while (n) {
      Node* nx = n->next[0];
      Node::destroy(n);
      n = nx;
    }
  }

  // max gap version over [begin, end)
  int64_t QueryMax(const Key& begin, const Key& end) const {
    if (begin >= end) return 0;
    const Node* n = head_;
    for (int l = kMaxLevel - 1; l >= 0; --l)
      while (n->next[l] && n->next[l]->key <= begin) n = n->next[l];
    // n = boundary of the gap containing begin; walk the covered gaps.
    int64_t mx = n->gap_version;
    for (n = n->next[0]; n && n->key < end; n = n->next[0])
      if (mx < n->gap_version) mx = n->gap_version;
    return mx;
  }

  // Assign `version` over [begin, end).  Versions are assigned monotonically
  // (enforced by ResolveBatch), so plain overwrite: split at end, drop
  // interior boundaries, set/insert the begin boundary.
  void Assign(const Key& begin, const Key& end, int64_t version) {
    if (begin >= end) return;
    Node* update[kMaxLevel];
    Node* n = head_;
    for (int l = kMaxLevel - 1; l >= 0; --l) {
      while (n->next[l] && n->next[l]->key < begin) n = n->next[l];
      update[l] = n;
    }
    // n = last boundary with key < begin; its gap covers begin unless an
    // exact-match node exists.
    Node* at_begin =
        (n->next[0] && n->next[0]->key == begin) ? n->next[0] : nullptr;
    if (at_begin)  // fold into update[] so interior unlinks see the true
      for (int l = 0; l < at_begin->level; ++l) update[l] = at_begin;
    // Value the keyspace resumes with at `end`: the version of the gap that
    // currently contains end.
    int64_t resume = (at_begin ? at_begin : n)->gap_version;
    Node* scan = (at_begin ? at_begin : n)->next[0];
    bool saw_end_exact = false;
    while (scan && scan->key <= end) {
      if (scan->key == end) {
        saw_end_exact = true;
        break;
      }
      resume = scan->gap_version;
      Node* nx = scan->next[0];
      Unlink_(update, scan);
      Node::destroy(scan);
      scan = nx;
    }
    if (at_begin) {
      at_begin->gap_version = version;
    } else if (n->gap_version != version) {  // left-coalesce if equal
      Node* nb = InsertAfter_(update, begin, version);
      for (int l = 0; l < nb->level; ++l) update[l] = nb;
    }
    if (!saw_end_exact && resume != version) InsertAfter_(update, end, resume);
    if (saw_end_exact && scan->gap_version == version) {
      // coalesce: the end boundary now carries the same value as [begin,end)
      Unlink_(update, scan);
      Node::destroy(scan);
    }
  }

  // GC: gaps older than the MVCC floor can never conflict a live snapshot
  // (TOO_OLD is decided first), so zero them and coalesce equal neighbours.
  void ClampBelow(int64_t floor) {
    Node* update[kMaxLevel];
    for (int l = 0; l < kMaxLevel; ++l) update[l] = head_;
    Node* n = head_;
    while (n) {
      if (n->gap_version < floor) n->gap_version = 0;
      Node* nx = n->next[0];
      if (n != head_ && n->gap_version == PrevValue_(update)) {
        Unlink_(update, n);
        Node::destroy(n);
      } else {
        for (int l = 0; l < n->level; ++l) update[l] = n;
      }
      n = nx;
    }
  }

  size_t NodeCount() const {
    size_t c = 0;
    for (Node* n = head_; n; n = n->next[0]) ++c;
    return c;
  }

 private:
  static int64_t PrevValue_(Node* const* update) {
    return update[0]->gap_version;
  }

  int RandomLevel_() {
    uint64_t x = rng_;
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    rng_ = x;
    int lvl = 1;
    while ((x & 3) == 0 && lvl < kMaxLevel) {  // p = 1/4 promotion
      ++lvl;
      x >>= 2;
    }
    return lvl;
  }

  // Insert a new node right after the positions recorded in update[].
  Node* InsertAfter_(Node* const* update, const Key& key, int64_t version) {
    int lvl = RandomLevel_();
    Node* nn = Node::make(key, version, lvl);
    for (int l = 0; l < lvl; ++l) {
      nn->next[l] = update[l]->next[l];
      update[l]->next[l] = nn;
    }
    return nn;
  }

  // Unlink `target`, known to be the immediate successor of update[l] at
  // every level it occupies.
  static void Unlink_(Node* const* update, Node* target) {
    for (int l = 0; l < target->level; ++l)
      if (update[l]->next[l] == target) update[l]->next[l] = target->next[l];
  }

  Node* head_;
  uint64_t rng_;
};

// Matches fdbserver/ConflictSet.h:36-40 TransactionCommitResult ordering
// (min-combine across resolvers relies on it; see conflict/api.py Verdict).
enum Verdict : uint8_t { kConflict = 0, kTooOld = 1, kCommitted = 2 };

class ConflictSetImpl {
 public:
  explicit ConflictSetImpl(int64_t oldest)
      : history_(0x5DEECE66DULL), oldest_(oldest), last_commit_(oldest) {}

  // Batch layout (see conflict/native.py): all range-endpoint keys of the
  // batch concatenated into key_bytes, delimited by key_offsets[n_keys+1],
  // ordered txn-by-txn as (read b,e)*nr then (write b,e)*nw.
  int ResolveBatch(int64_t commit_version, int32_t n_txn,
                   const int64_t* snapshots, const int32_t* n_read_ranges,
                   const int32_t* n_write_ranges, const uint8_t* key_bytes,
                   const int64_t* key_offsets, uint8_t* out_verdicts) {
    if (commit_version <= last_commit_) return -1;
    last_commit_ = commit_version;
    size_t key_idx = 0;
    auto next_key = [&]() {
      const int64_t b = key_offsets[key_idx], e = key_offsets[key_idx + 1];
      ++key_idx;
      return Key(reinterpret_cast<const char*>(key_bytes) + b,
                 static_cast<size_t>(e - b));
    };
    batch_writes_.clear();
    committed_writes_.clear();
    for (int32_t t = 0; t < n_txn; ++t) {
      const int32_t nr = n_read_ranges[t], nw = n_write_ranges[t];
      if (snapshots[t] < oldest_) {  // decided at add time, SkipList.cpp:985
        out_verdicts[t] = kTooOld;
        key_idx += 2 * (nr + nw);
        continue;
      }
      bool conflict = false;
      for (int32_t i = 0; i < nr; ++i) {
        Key b = next_key(), e = next_key();
        if (conflict || b >= e) continue;
        if (history_.QueryMax(b, e) > snapshots[t] || BatchOverlap_(b, e))
          conflict = true;
      }
      if (conflict) {
        out_verdicts[t] = kConflict;
        key_idx += 2 * nw;
        continue;
      }
      out_verdicts[t] = kCommitted;
      for (int32_t i = 0; i < nw; ++i) {
        Key b = next_key(), e = next_key();
        if (b >= e) continue;
        BatchInsert_(b, e);
        committed_writes_.emplace_back(std::move(b), std::move(e));
      }
    }
    for (auto& [b, e] : committed_writes_)
      history_.Assign(b, e, commit_version);
    return 0;
  }

  void RemoveBefore(int64_t version) {
    if (version <= oldest_) return;
    oldest_ = version;
    history_.ClampBelow(version);
  }

  int64_t oldest() const { return oldest_; }
  size_t node_count() const { return history_.NodeCount(); }

 private:
  // Intra-batch committed-writes index: coalesced disjoint intervals in a
  // flat map (covers the reference MiniConflictSet's ordered "later txns see
  // earlier committed writes" semantics, SkipList.cpp:1028-1152).
  bool BatchOverlap_(const Key& b, const Key& e) const {
    auto it = batch_writes_.upper_bound(b);
    if (it != batch_writes_.begin()) {
      auto prev = std::prev(it);
      if (b < prev->second) return true;  // interval starting <= b covers b
    }
    return it != batch_writes_.end() && it->first < e;
  }

  void BatchInsert_(const Key& b, const Key& e) {
    Key nb = b, ne = e;
    auto it = batch_writes_.upper_bound(b);
    if (it != batch_writes_.begin()) {
      auto prev = std::prev(it);
      if (prev->second >= b) {  // merge with left neighbour
        nb = prev->first;
        if (prev->second > ne) ne = prev->second;
        batch_writes_.erase(prev);
      }
    }
    it = batch_writes_.lower_bound(nb);
    while (it != batch_writes_.end() && it->first <= ne) {
      if (it->second > ne) ne = it->second;
      it = batch_writes_.erase(it);
    }
    batch_writes_.emplace(std::move(nb), std::move(ne));
  }

  SkipListStepFunction history_;
  std::map<Key, Key> batch_writes_;  // begin -> end, disjoint, coalesced
  std::vector<std::pair<Key, Key>> committed_writes_;
  int64_t oldest_;
  int64_t last_commit_;
};

}  // namespace

extern "C" {

// Plugin ABI (loaded via conflict/plugin.py; pattern: fdbrpc/LoadPlugin.h).
const char* fdbtpu_conflictset_backend_name() { return "skiplist-cpp"; }

void* fdbtpu_conflictset_create(int64_t oldest_version) {
  return new ConflictSetImpl(oldest_version);
}

void fdbtpu_conflictset_destroy(void* cs) {
  delete static_cast<ConflictSetImpl*>(cs);
}

int fdbtpu_conflictset_resolve(void* cs, int64_t commit_version, int32_t n_txn,
                               const int64_t* snapshots,
                               const int32_t* n_read_ranges,
                               const int32_t* n_write_ranges,
                               const uint8_t* key_bytes,
                               const int64_t* key_offsets,
                               uint8_t* out_verdicts) {
  return static_cast<ConflictSetImpl*>(cs)->ResolveBatch(
      commit_version, n_txn, snapshots, n_read_ranges, n_write_ranges,
      key_bytes, key_offsets, out_verdicts);
}

void fdbtpu_conflictset_remove_before(void* cs, int64_t version) {
  static_cast<ConflictSetImpl*>(cs)->RemoveBefore(version);
}

int64_t fdbtpu_conflictset_oldest(void* cs) {
  return static_cast<ConflictSetImpl*>(cs)->oldest();
}

int64_t fdbtpu_conflictset_node_count(void* cs) {
  return static_cast<int64_t>(static_cast<ConflictSetImpl*>(cs)->node_count());
}

}  // extern "C"
