"""foundationdb_tpu — a TPU-native distributed transactional key-value
framework with the capabilities of FoundationDB (reference: atn34/foundationdb
@ 6.1.0, surveyed in SURVEY.md).

Layer map (mirrors the reference bottom-up; see SURVEY.md section 1):
  runtime/   deterministic async core + simulation clock   (flow/, Sim2)
  rpc/       sim network, typed endpoints, failure monitor (fdbrpc/)
  keys.py    fixed-width key encoding for device kernels
  ops/       JAX building blocks (search, RMQ, bitset scans)
  conflict/  the OCC ConflictSet: oracle, native C++, TPU   (fdbserver/SkipList.cpp)
  parallel/  multi-device sharded resolver (shard_map+psum) (multi-resolver split)
  roles/     sequencer, proxy, resolver, tlog, storage      (fdbserver/)
  client/    Transaction + ReadYourWrites                   (fdbclient/)
  workloads/ simulation test workloads                      (fdbserver/workloads/)
"""

__version__ = "0.1.0"
