"""Fault-injection hooks — BUGGIFY (flow/flow.h:65, flow/FaultInjection.h).

In simulation, `buggify()` fires rare branches at random so seldom-taken
paths get exercised; in production it is always False.  Each call site is
independently enabled per run (the reference's per-SBVar state,
flow/flow.cpp:189-214): an enabled site fires with `fire_prob` each time.

Every query also feeds a per-site census — armed vs fired counts for the
run — which `emit_coverage(trace)` lands in the trace plane as
`CodeCoverage` events at sim teardown.  The soak driver (tools/soak.py)
merges those across seeds: a site that ARMS across a campaign but never
FIRES is exactly the "fault injection silently stopped injecting" failure
the reference's coveragetool discipline exists to catch."""

from __future__ import annotations

from .core import DeterministicRandom, TaskPriority

_state: dict[str, bool] = {}
_forced: dict[str, int] = {}
_fires: dict[str, int] = {}
_rng: DeterministicRandom | None = None
_enable_prob = 0.25
_fire_prob = 0.05


def enable(rng: DeterministicRandom, enable_prob: float = 0.25, fire_prob: float = 0.05) -> None:
    global _rng, _enable_prob, _fire_prob
    _rng = rng.split()
    _enable_prob = enable_prob
    _fire_prob = fire_prob
    _state.clear()
    _forced.clear()
    _fires.clear()


def disable() -> None:
    global _rng
    _rng = None
    _state.clear()
    _forced.clear()
    _fires.clear()


def force(site: str, times: int = 1) -> None:
    """Arm `site` to fire deterministically on its next `times` queries —
    the campaign/test hook that makes a rare site's firing *required*
    rather than probabilistic (the reference's per-SBVar forcing used by
    targeted simulation tests).  Only honored in simulation (enable()d);
    draws no randomness, so forcing never perturbs the seeded RNG stream."""
    _forced[site] = _forced.get(site, 0) + times


def is_enabled() -> bool:
    return _rng is not None


def buggify(site: str) -> bool:
    fired = _buggify(site)
    if fired:
        from .coverage import testcov

        testcov(f"buggify.{site}")
    return fired


def _buggify(site: str) -> bool:
    """True rarely, only in simulation.  `site` identifies the call site."""
    if _rng is None:
        return False
    n = _forced.get(site, 0)
    if n > 0:
        if n == 1:
            del _forced[site]
        else:
            _forced[site] = n - 1
        _fires[site] = _fires.get(site, 0) + 1
        return True
    if site not in _state:
        _state[site] = _rng.coinflip(_enable_prob)
    if _state[site] and _rng.coinflip(_fire_prob):
        _fires[site] = _fires.get(site, 0) + 1
        return True
    return False


def census() -> dict[str, dict]:
    """Per-site `{"armed": bool, "fires": int}` for every site queried,
    fired, OR force()d this run.  A forced site counts as armed even when
    its guard was never reached (pending `_forced` budget): something
    deliberately pointed the campaign at it, and armed-with-zero-fires is
    exactly the silently-stopped-injecting row the census exists to
    surface."""
    out: dict[str, dict] = {
        site: {"armed": armed, "fires": _fires.get(site, 0)}
        for site, armed in _state.items()
    }
    for site, n in _fires.items():
        if site not in out:
            out[site] = {"armed": True, "fires": n}
    for site in _forced:
        if site in out:
            out[site]["armed"] = True
        else:
            out[site] = {"armed": True, "fires": _fires.get(site, 0)}
    return out


def snapshot() -> dict:
    """Full module state, for save/restore around a test (conftest pairs
    this with coverage.snapshot so census numbers are per-test)."""
    return {
        "state": dict(_state),
        "forced": dict(_forced),
        "fires": dict(_fires),
        "rng": _rng,
        "enable_prob": _enable_prob,
        "fire_prob": _fire_prob,
    }


def restore(snap: dict) -> None:
    global _rng, _enable_prob, _fire_prob
    _state.clear()
    _state.update(snap["state"])
    _forced.clear()
    _forced.update(snap["forced"])
    _fires.clear()
    _fires.update(snap["fires"])
    _rng = snap["rng"]
    _enable_prob = snap["enable_prob"]
    _fire_prob = snap["fire_prob"]


def emit_coverage(trace) -> None:
    """One `CodeCoverage` trace event per queried site — the sim-teardown
    emission (CODE_COVERAGE_SCHEMA in control/status.py) the soak driver
    merges across seeds.  Emit BEFORE disable(): disabling clears the
    census."""
    for site, row in sorted(census().items()):
        trace.trace("CodeCoverage", Name=site, Kind="buggify",
                    Hits=row["fires"], Armed=row["armed"])


async def maybe_delay(loop, site: str, seconds: float = 0.02) -> None:
    """Rare injected delay at `site` (no-op outside simulation chaos mode).
    The classic BUGGIFY(delay(...)) pattern the reference sprinkles through
    every role (e.g. TLogServer.actor.cpp, MasterProxyServer.actor.cpp)."""
    if buggify(site):
        await loop.delay(seconds, TaskPriority.DEFAULT_ENDPOINT)
