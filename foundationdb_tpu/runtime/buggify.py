"""Fault-injection hooks — BUGGIFY (flow/flow.h:65, flow/FaultInjection.h).

In simulation, `buggify()` fires rare branches at random so seldom-taken
paths get exercised; in production it is always False.  Each call site is
independently enabled per run (the reference's per-SBVar state,
flow/flow.cpp:189-214): an enabled site fires with `fire_prob` each time.
"""

from __future__ import annotations

from .core import DeterministicRandom, TaskPriority

_state: dict[str, bool] = {}
_forced: dict[str, int] = {}
_rng: DeterministicRandom | None = None
_enable_prob = 0.25
_fire_prob = 0.05


def enable(rng: DeterministicRandom, enable_prob: float = 0.25, fire_prob: float = 0.05) -> None:
    global _rng, _enable_prob, _fire_prob
    _rng = rng.split()
    _enable_prob = enable_prob
    _fire_prob = fire_prob
    _state.clear()
    _forced.clear()


def disable() -> None:
    global _rng
    _rng = None
    _state.clear()
    _forced.clear()


def force(site: str, times: int = 1) -> None:
    """Arm `site` to fire deterministically on its next `times` queries —
    the campaign/test hook that makes a rare site's firing *required*
    rather than probabilistic (the reference's per-SBVar forcing used by
    targeted simulation tests).  Only honored in simulation (enable()d);
    draws no randomness, so forcing never perturbs the seeded RNG stream."""
    _forced[site] = _forced.get(site, 0) + times


def is_enabled() -> bool:
    return _rng is not None


def buggify(site: str) -> bool:
    fired = _buggify(site)
    if fired:
        from .coverage import testcov

        testcov(f"buggify.{site}")
    return fired


def _buggify(site: str) -> bool:
    """True rarely, only in simulation.  `site` identifies the call site."""
    if _rng is None:
        return False
    n = _forced.get(site, 0)
    if n > 0:
        if n == 1:
            del _forced[site]
        else:
            _forced[site] = n - 1
        return True
    if site not in _state:
        _state[site] = _rng.coinflip(_enable_prob)
    return _state[site] and _rng.coinflip(_fire_prob)


async def maybe_delay(loop, site: str, seconds: float = 0.02) -> None:
    """Rare injected delay at `site` (no-op outside simulation chaos mode).
    The classic BUGGIFY(delay(...)) pattern the reference sprinkles through
    every role (e.g. TLogServer.actor.cpp, MasterProxyServer.actor.cpp)."""
    if buggify(site):
        await loop.delay(seconds, TaskPriority.DEFAULT_ENDPOINT)
