"""Structured trace events + metrics — the TraceEvent system (flow/Trace.h:137).

Events are dicts with severity/type/fields, collected per-process by a
TraceCollector: in tests/simulation they stay in memory for assertions; in
production they stream to JSONL files (the reference rolls XML files).
`track_latest` retains the newest event per key — the transport the status
subsystem scrapes (fdbserver/Status.actor.cpp:1698 reads trackLatest
snapshots).  Counters mirror flow/Stats.h:57 CounterCollection.
"""

from __future__ import annotations

import json
from typing import Any, Callable, TextIO


SEV_DEBUG, SEV_INFO, SEV_WARN, SEV_WARN_ALWAYS, SEV_ERROR = 5, 10, 20, 30, 40


class TraceCollector:
    def __init__(self, clock: Callable[[], float] | None = None,
                 sink: TextIO | None = None, keep: int = 50000) -> None:
        self._clock = clock or (lambda: 0.0)
        self._sink = sink
        self._keep = keep
        self.events: list[dict[str, Any]] = []
        self.latest: dict[str, dict[str, Any]] = {}
        self._suppressed: dict[str, int] = {}

    def trace(self, event_type: str, severity: int = SEV_INFO,
              track_latest: str | None = None, **fields: Any) -> dict[str, Any]:
        ev = {"Type": event_type, "Severity": severity, "Time": self._clock(), **fields}
        if len(self.events) < self._keep:
            self.events.append(ev)
        else:
            self._suppressed[event_type] = self._suppressed.get(event_type, 0) + 1
        if track_latest is not None:
            self.latest[track_latest] = ev
        if self._sink is not None:
            json.dump(ev, self._sink, default=str)
            self._sink.write("\n")
        return ev

    def find(self, event_type: str) -> list[dict[str, Any]]:
        return [e for e in self.events if e["Type"] == event_type]

    def count(self, event_type: str) -> int:
        return len(self.find(event_type)) + self._suppressed.get(event_type, 0)


class TraceBatch:
    """Per-transaction pipeline timelines — the g_traceBatch analog
    (flow/Trace.h:253; the reference emits TransactionDebug/CommitDebug
    events keyed by a sampled debug ID at every pipeline station, and tools
    reconstruct a transaction's journey by joining on the ID).

    A module global, exactly like the reference's: role code at any layer
    calls `g_trace_batch.add(location, debug_id)` without plumbing a
    collector through every constructor.  The newest cluster attaches its
    clock; tests read `timeline(debug_id)`."""

    def __init__(self) -> None:
        self.events: list[dict[str, Any]] = []
        self.suppressed = 0
        self._clock: Callable[[], float] = lambda: 0.0
        self._keep = 100_000

    def attach_clock(self, clock: Callable[[], float]) -> None:
        """Bind the newest cluster's clock AND start a fresh event log: two
        same-seed clusters derive identical debug IDs, so carrying events
        across would interleave different runs under one ID (and pin the
        previous cluster's loop in memory via the old clock closure)."""
        self._clock = clock
        self.clear()

    def add(self, location: str, debug_id: str | None) -> None:
        if debug_id is None:
            return
        if len(self.events) < self._keep:
            self.events.append(
                {"Time": self._clock(), "Location": location, "ID": debug_id}
            )
        else:
            self.suppressed += 1

    def timeline(self, debug_id: str) -> list[dict[str, Any]]:
        return sorted(
            (e for e in self.events if e["ID"] == debug_id),
            key=lambda e: e["Time"],
        )

    def clear(self) -> None:
        self.events = []
        self.suppressed = 0


g_trace_batch = TraceBatch()


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str, collection: "CounterCollection | None" = None) -> None:
        self.name = name
        self.value = 0
        if collection is not None:
            collection.add(self)

    def add(self, n: int = 1) -> None:
        self.value += n

    __iadd__ = None  # use .add()


class CounterCollection:
    def __init__(self, name: str) -> None:
        self.name = name
        self.counters: list[Counter] = []

    def add(self, c: Counter) -> None:
        self.counters.append(c)

    def counter(self, name: str) -> Counter:
        return Counter(name, self)

    def snapshot(self) -> dict[str, int]:
        return {c.name: c.value for c in self.counters}
