"""Structured trace events + metrics — the TraceEvent system (flow/Trace.h:137).

Events are dicts with severity/type/fields, collected per-process by a
TraceCollector: in tests/simulation they stay in memory for assertions; in
production they stream to rolling JSONL files (the reference rolls XML
files under --maxlogssize/--maxlogs; `TraceFileSink` is that analog).
`track_latest` retains the newest event per key — the transport the status
subsystem scrapes (fdbserver/Status.actor.cpp:1698 reads trackLatest
snapshots).  Counters mirror flow/Stats.h:57 CounterCollection, including
the periodic rate-converted `*Metrics` emission every role runs
(`spawn_role_metrics`).
"""

from __future__ import annotations

import json
import os
import time as _time
from collections import deque
from typing import Any, Callable


SEV_DEBUG, SEV_INFO, SEV_WARN, SEV_WARN_ALWAYS, SEV_ERROR = 5, 10, 20, 30, 40


# The SEV_WARN+ event vocabulary — the status-schema discipline applied to
# warning traces (the reference checks status docs against Schemas.cpp; we
# check WARN+ trace call sites against this set).  Every `trace(...)` call
# site with severity SEV_WARN or above must name EXACTLY ONE entry here,
# and each entry must have exactly one call site, so a new warning event
# can never silently shadow an existing one in `track_latest` or the
# operator message list (tests/test_trace_plane.py walks the codebase).
WARN_EVENT_TYPES = frozenset({
    "TransportFrameRejected",    # rpc/transport.py: length-corrupt header
    "TransportDecodeFailed",     # rpc/transport.py: undecodable frame body
    "TransportProtocolMismatch", # rpc/transport.py: mixed-version peer
    "RkUpdate",                  # control/ratekeeper.py: limiting reason
    "SlowTask",                  # runtime/core.py: run-loop callback over
                                 # SLOW_TASK_THRESHOLD host wall seconds
    "SoakSeedFailed",            # tools/soak.py: a campaign seed's verdict
                                 # with the failure, for triage scrapes
    "BlobRequestRetried",        # storage/blobstore.py: one blob-store
                                 # retry (backoff in flight); soak triage
                                 # summarizes retry storms per seed
    "IoTimeoutKilled",           # storage/files.py: a disk sync stalled
                                 # past IO_TIMEOUT_S fail-fasted its
                                 # process (kill/recovery takes over)
    "TLogCommitRefused",         # roles/tlog.py: queue past
                                 # TLOG_HARD_LIMIT_BYTES — commit refused,
                                 # never silently acked
    "TLogDiskError",             # roles/tlog.py: the durable log's disk
                                 # refused (ENOSPC/injected error); the
                                 # push is unacked and the proxy escalates
    "ProcessDied",               # tools/fdbmonitor.py: a supervised OS
                                 # process exited (Section/Pid/ExitCode);
                                 # soak triage folds these into
                                 # first_events per artifact dir
    "MonitorConfInvalid",        # tools/fdbmonitor.py: torn/unparseable
                                 # conf — the LAST GOOD conf stays live
                                 # (never kill the world over a half-save)
})


class TraceFileSink:
    """Rolling line-buffered JSONL trace files — the reference's rolling
    trace files (`--maxlogssize` / `--maxlogs`, flow/Trace.cpp).  Lines go
    to `<path>.<seq>.jsonl`; once the current file passes `roll_size`
    bytes the NEXT line opens `<seq+1>`, and files older than `max_logs`
    generations are deleted.  Line-buffered (buffering=1): every event is
    flushed to the OS as it is written, so a crashed process loses at most
    the line being formatted — the crash-safe property operators rely on
    to debug the crash itself."""

    def __init__(self, path: str, roll_size: int = 10 << 20,
                 max_logs: int = 10) -> None:
        self.path = path
        self.roll_size = int(roll_size)
        self.max_logs = max(int(max_logs), 1)
        # resume after the newest existing generation rather than appending
        # to (and re-rolling) a previous run's files — scan the DIRECTORY
        # for the highest sequence, since a previous run's pruning leaves a
        # gap at the low numbers (stepping up from 0 would stop there and
        # collide with the old run's surviving files)
        base = os.path.basename(path)
        seqs = []
        for f in os.listdir(os.path.dirname(path) or "."):
            if f.startswith(base + ".") and f.endswith(".jsonl"):
                mid = f[len(base) + 1 : -len(".jsonl")]
                if mid.isdigit():
                    seqs.append(int(mid))
        self._seq = max(seqs) + 1 if seqs else 0
        self._f = None
        self._bytes = 0
        self._open()

    def _fname(self, seq: int) -> str:
        return f"{self.path}.{seq}.jsonl"

    def _open(self) -> None:
        self._f = open(self._fname(self._seq), "a", buffering=1)
        self._bytes = self._f.tell()

    @property
    def current_file(self) -> str:
        return self._fname(self._seq)

    def files(self) -> list[str]:
        """Every generation still on disk, oldest first."""
        return [
            self._fname(s) for s in range(self._seq + 1)
            if os.path.exists(self._fname(s))
        ]

    def write(self, line: str) -> None:
        if self._bytes > 0 and self._bytes + len(line) > self.roll_size:
            self._roll()
        self._f.write(line)
        self._bytes += len(line)

    def _roll(self) -> None:
        self._f.close()
        self._seq += 1
        self._open()
        stale = self._seq - self.max_logs
        if stale >= 0:
            try:
                os.remove(self._fname(stale))
            except OSError:
                pass

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class TraceCollector:
    """Per-process event collector.  The event ring is a flight recorder:
    `keep` bounds memory and the ring keeps the NEWEST events (old ones
    are overwritten — `count()` still reports every event ever traced).
    `min_severity` drops events below the `TRACE_SEVERITY` knob entirely;
    `machine` (when set) stamps a host/process identity on every event for
    cross-process trace joins (tools/trace_tool.py)."""

    def __init__(self, clock: Callable[[], float] | None = None,
                 sink=None, keep: int = 50000,
                 min_severity: int = SEV_DEBUG,
                 machine: str | None = None,
                 wall_clock: Callable[[], float] | None = None) -> None:
        self._clock = clock or (lambda: 0.0)
        # the clock behind the file lines' WallTime stamp.  Real processes
        # keep the default (cross-process trace joins need a SHARED clock,
        # which only the host wall provides); deterministic sim clusters
        # bind their virtual clock instead, so a seed's rolled trace files
        # are byte-stable across reruns — same discipline as the reference,
        # where sim trace time is g_network->now().  The one sanctioned
        # exception is SlowTask: its DurationS payload is a HOST-wall
        # measurement of a reactor stall (runtime/core.py) — profiling
        # data virtual time cannot see — so those lines may differ
        # between reruns (tests/test_flowlint.py pins the carve-out)
        self._wall_clock = wall_clock or _time.time  # flowlint: ok wall-clock (default for real processes; sim binds the sim clock)
        self._sink = sink  # TextIO or TraceFileSink: anything with write(str)
        self.min_severity = min_severity
        self.machine = machine
        self.events: deque[dict[str, Any]] = deque(maxlen=keep)
        self.latest: dict[str, dict[str, Any]] = {}
        self._counts: dict[str, int] = {}

    def trace(self, event_type: str, severity: int = SEV_INFO,
              track_latest: str | None = None, **fields: Any) -> dict[str, Any]:
        ev = {"Type": event_type, "Severity": severity, "Time": self._clock(), **fields}
        if self.machine is not None:
            ev["Machine"] = self.machine
        if severity < self.min_severity:
            return ev
        self._counts[event_type] = self._counts.get(event_type, 0) + 1
        self.events.append(ev)
        if track_latest is not None:
            self.latest[track_latest] = ev
        if self._sink is not None:
            # WallTime rides only the FILE copy: cross-process joins need a
            # shared clock (each process's `Time` has its own origin), and
            # the in-memory events deterministic sim tests read must not
            # carry wall time
            try:
                self._sink.write(
                    json.dumps({**ev, "WallTime": self._wall_clock()},
                               default=str)
                    + "\n"
                )
            except OSError:
                pass  # a full disk must not kill the process
        return ev

    def find(self, event_type: str) -> list[dict[str, Any]]:
        return [e for e in self.events if e["Type"] == event_type]

    def count(self, event_type: str) -> int:
        """Events of this type ever traced — INCLUDING ones the ring has
        since overwritten (a flight recorder forgets the payload, not the
        count)."""
        return self._counts.get(event_type, 0)


class TraceBatch:
    """Per-transaction pipeline timelines — the g_traceBatch analog
    (flow/Trace.h:253; the reference emits TransactionDebug/CommitDebug
    events keyed by a sampled debug ID at every pipeline station, and tools
    reconstruct a transaction's journey by joining on the ID).

    A module global, exactly like the reference's: role code at any layer
    calls `g_trace_batch.add(location, debug_id)` without plumbing a
    collector through every constructor.  The newest cluster attaches its
    clock AND (when given) its TraceCollector, so every station also lands
    in the collector as a `TransactionDebug` event — which is how stations
    reach the per-process trace FILES that tools/trace_tool.py joins across
    processes; tests read `timeline(debug_id)` in memory."""

    def __init__(self) -> None:
        self.events: list[dict[str, Any]] = []
        self.suppressed = 0
        self._clock: Callable[[], float] = lambda: 0.0
        self._collector: TraceCollector | None = None
        self._keep = 100_000

    def attach_clock(self, clock: Callable[[], float],
                     collector: TraceCollector | None = None) -> None:
        """Bind the newest cluster's clock AND start a fresh event log: two
        same-seed clusters derive identical debug IDs, so carrying events
        across would interleave different runs under one ID (and pin the
        previous cluster's loop in memory via the old clock closure).
        `collector` additionally mirrors every station into that cluster's
        TraceCollector (and thus its trace files)."""
        self._clock = clock
        self._collector = collector
        self.clear()

    def add(self, location: str, debug_id: str | None) -> None:
        if debug_id is None:
            return
        if len(self.events) < self._keep:
            self.events.append(
                {"Time": self._clock(), "Location": location, "ID": debug_id}
            )
        else:
            self.suppressed += 1
        if self._collector is not None:
            self._collector.trace(
                "TransactionDebug", Location=location, ID=debug_id
            )

    def timeline(self, debug_id: str) -> list[dict[str, Any]]:
        return sorted(
            (e for e in self.events if e["ID"] == debug_id),
            key=lambda e: e["Time"],
        )

    def clear(self) -> None:
        self.events = []
        self.suppressed = 0


g_trace_batch = TraceBatch()


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str, collection: "CounterCollection | None" = None) -> None:
        self.name = name
        self.value = 0
        if collection is not None:
            collection.add(self)

    def add(self, n: int = 1) -> None:
        self.value += n

    __iadd__ = None  # use .add()


class CounterCollection:
    def __init__(self, name: str) -> None:
        self.name = name
        self.counters: list[Counter] = []
        self._prev: dict[str, int] | None = None
        self._prev_time = 0.0

    def add(self, c: Counter) -> None:
        self.counters.append(c)

    def counter(self, name: str) -> Counter:
        return Counter(name, self)

    def snapshot(self) -> dict[str, int]:
        return {c.name: c.value for c in self.counters}

    def rates(self, now: float) -> dict[str, float]:
        """Per-second deltas since the previous rates() call — the
        Counter::getRate analog (flow/Stats.h): `*Metrics` events and
        status report RATES over the emission interval, not lifetime
        totals.  The first call (no remembered snapshot) reports zeros and
        arms the baseline."""
        cur = self.snapshot()
        prev, prev_t = self._prev, self._prev_time
        self._prev, self._prev_time = cur, now
        dt = now - prev_t
        if prev is None or dt <= 0:
            return {k: 0.0 for k in cur}
        return {k: (v - prev.get(k, 0)) / dt for k, v in cur.items()}


def spawn_role_metrics(loop, process, trace: TraceCollector, event_type: str,
                       fields_fn: Callable[[], dict], interval: float,
                       priority: int = 0, instance: str | None = None):
    """Periodic `<Role>Metrics` trace emission — the reference's
    CounterCollection cadence (flow/Stats.h:57 traceCounters): every
    `interval` (simulated) seconds the role's `fields_fn()` snapshot lands
    in the cluster's collector, `track_latest`-keyed per role instance so
    status always holds the newest sample while the event stream carries
    the time-series.

    `process` bounds the emitter's life: a deposed directly-constructed
    role loses its process without `stop()` ever being called, and a stale
    generation's emitter must not keep narrating over its successor's.
    Pass None for emitters not tied to a process (the network fabric)."""

    name = instance or (process.name if process is not None else event_type)
    try:
        fields_fn()  # arm the rate baselines NOW, so the first emission
    except Exception:  # reports the first interval's real deltas, not zeros
        pass

    async def emit() -> None:
        last = loop.now()
        while True:
            await loop.delay(interval, priority)
            if process is not None and not process.alive:
                return
            now = loop.now()
            trace.trace(
                event_type,
                track_latest=f"{event_type}:{name}",
                Elapsed=now - last,
                # per-instance attribution IN the event too: several
                # same-role emitters in one process must stay separable in
                # the event stream / trace files, not just in track_latest
                Instance=name,
                **fields_fn(),
            )
            last = now

    return loop.spawn(emit(), priority, f"metrics-{event_type}")


def spawn_wire_metrics(loop, trace: TraceCollector, wire, interval: float,
                       source: str, priority: int = 0, process=None):
    """WireStats delta emission (`WireMetrics`): the transport's slice of
    the periodic metrics plane — codec frame/byte rates plus the cumulative
    pickle-fallback and coalescing counters (docs/WIRE.md)."""
    prev: dict = {}

    def fields() -> dict:
        snap = wire.snapshot()
        dt = max(loop.now() - prev.get("_t", loop.now() - interval), 1e-9)
        out = {
            "Source": source,
            "FramesEncodedPerSec":
                (snap["frames_encoded"] - prev.get("frames_encoded", 0)) / dt,
            "FramesDecodedPerSec":
                (snap["frames_decoded"] - prev.get("frames_decoded", 0)) / dt,
            "BytesEncodedPerSec":
                (snap["bytes_encoded"] - prev.get("bytes_encoded", 0)) / dt,
            "BytesDecodedPerSec":
                (snap["bytes_decoded"] - prev.get("bytes_decoded", 0)) / dt,
            "PickleFallbacks": snap["pickle_fallbacks"],
            "DecodeFallbacks": snap["decode_fallbacks"],
            "FramesPerFlush": snap["frames_per_flush"],
        }
        prev.update(snap)
        prev["_t"] = loop.now()
        return out

    return spawn_role_metrics(
        loop, process, trace, "WireMetrics", fields, interval, priority,
        instance=source,
    )
