"""Deterministic cooperative runtime — the framework's flow/ analog.

The reference is written in Flow: futures/promises + actors compiled to
callback state machines, all scheduled by a single-threaded priority run
loop (flow/flow.h:595,709; flow/Net2.actor.cpp:548).  Its deepest property
is *substitutability of the world*: the same role code runs under the real
event loop or under a seeded simulator, making whole-cluster runs
deterministic and replayable (flow/network.h:192 INetwork; fdbrpc/sim2).

This runtime keeps that property with idiomatic Python instead of a Flow
port: native coroutines (`async def`) are the actors, `Future`/`Promise`
the single-assignment channels, and `EventLoop` a virtual-clock priority
scheduler.  Everything is deterministic by construction: the loop is
single-threaded, timers fire in (time, priority, seq) order, and all
randomness flows from `DeterministicRandom` seeds.  Python-level control
flow is *not* the data path — the data path is the device kernel and the
native backends; this loop only sequences batches, RPCs and role logic,
mirroring how the reference's run loop sequences single-threaded actors
around its hot C++ cores (SURVEY §2.6.6).

The real-time twin (`RealClockDriver`) drives the same loop off the wall
clock; roles cannot observe which world they run in — the Net2/Sim2 seam.
"""

from __future__ import annotations

import heapq
import random as _pyrandom
import time as _time
from collections import deque
from typing import Any, Awaitable, Callable, Coroutine, Iterable


class TaskPriority:
    """Fixed task priorities ordering everything in the run loop (the
    reference's 40-step enum, flow/network.h:30-74; higher runs first)."""

    MAX = 1000000
    RUN_LOOP = 30000
    WRITE_SOCKET = 10000
    COORDINATION = 8800
    PROXY_COMMIT = 8540
    RESOLVER = 8700
    TLOG_COMMIT = 8510
    GET_LIVE_VERSION = 8500
    DEFAULT_DELAY = 7010
    DISK_IO = 5010  # reference TaskDiskIOComplete
    DEFAULT_ENDPOINT = 5000
    UNKNOWN_ENDPOINT = 4000
    RATEKEEPER = 3110
    STORAGE_SERVER = 3100
    DATA_DISTRIBUTION = 3500
    LOW = 2000
    MIN = 1000
    ZERO = 0


class ActorCancelled(Exception):
    """Raised inside a coroutine when its Task is cancelled (the reference's
    actor_cancelled, thrown by actor destruction — flow/Error.h)."""


class BrokenPromise(Exception):
    """The promise side was dropped without a value (flow/flow.h SAV)."""


class TimedOut(Exception):
    pass


_PENDING = object()


class Future:
    """Single-assignment async value (flow/flow.h:595).

    Not thread-safe by design: the whole runtime is single-threaded, like
    the reference's per-process run loop.
    """

    __slots__ = ("_value", "_error", "_callbacks")

    def __init__(self) -> None:
        self._value: Any = _PENDING
        self._error: BaseException | None = None
        self._callbacks: list[Callable[[Future], None]] = []

    # -- inspection --------------------------------------------------------
    def done(self) -> bool:
        return self._value is not _PENDING or self._error is not None

    def result(self) -> Any:
        if self._error is not None:
            raise self._error
        if self._value is _PENDING:
            raise RuntimeError("future not ready")
        return self._value

    def exception(self) -> BaseException | None:
        return self._error

    # -- completion (used by Promise / Task) -------------------------------
    def _set(self, value: Any) -> None:
        if self.done():
            raise RuntimeError("future already set")
        self._value = value
        self._fire()

    def _set_error(self, err: BaseException) -> None:
        if self.done():
            raise RuntimeError("future already set")
        self._error = err
        self._fire()

    def _fire(self) -> None:
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def add_done_callback(self, cb: Callable[[Future], None]) -> None:
        if self.done():
            cb(self)
        else:
            self._callbacks.append(cb)

    def remove_done_callback(self, cb: Callable[[Future], None]) -> None:
        try:
            self._callbacks.remove(cb)
        except ValueError:
            pass

    def __await__(self):
        if not self.done():
            yield self
        return self.result()


class Promise:
    """Write side of a Future (flow/flow.h:709).  Dropping a pending promise
    breaks it: awaiters see BrokenPromise, exactly like the reference."""

    __slots__ = ("future", "_sent")

    def __init__(self) -> None:
        self.future = Future()
        self._sent = False

    def send(self, value: Any = None) -> None:
        self._sent = True
        self.future._set(value)

    def fail(self, err: BaseException) -> None:
        self._sent = True
        self.future._set_error(err)

    def is_set(self) -> bool:
        return self.future.done()

    def __del__(self) -> None:
        if not self._sent and not self.future.done():
            try:
                self.future._set_error(BrokenPromise())
            except Exception:
                pass


class FutureStream:
    """Multi-value channel (flow/flow.h:760 FutureStream): awaiting pops the
    next queued value; values queue if nobody is waiting."""

    __slots__ = ("_queue", "_waiters", "_closed_err")

    def __init__(self) -> None:
        self._queue: deque[Any] = deque()
        self._waiters: deque[Promise] = deque()
        self._closed_err: BaseException | None = None

    def send(self, value: Any) -> None:
        if self._waiters:
            self._waiters.popleft().send(value)
        else:
            self._queue.append(value)

    def close(self, err: BaseException | None = None) -> None:
        self._closed_err = err or BrokenPromise()
        for w in self._waiters:
            w.fail(self._closed_err)
        self._waiters.clear()

    def pop(self) -> Future:
        p = Promise()
        if self._queue:
            p.send(self._queue.popleft())
        elif self._closed_err is not None:
            p.fail(self._closed_err)
        else:
            self._waiters.append(p)
        return p.future

    def __len__(self) -> int:
        return len(self._queue)


class Task(Future):
    """A running coroutine; also a Future of its result.  Cancellation
    throws ActorCancelled at the coroutine's current await point — the
    Python rendering of "actor destroyed ⇒ wait() throws actor_cancelled"
    (flow/flow.h:914 Actor)."""

    __slots__ = (
        "_coro", "_loop", "_priority", "_waiting_on", "name", "_resume_cb",
        "_cancelled", "_started",
    )

    def __init__(self, coro: Coroutine, loop: "EventLoop", priority: int, name: str) -> None:
        super().__init__()
        self._coro = coro
        self._loop = loop
        self._priority = priority
        self._waiting_on: Future | None = None
        self._resume_cb: Callable | None = None
        self._cancelled = False
        self._started = False
        self.name = name

    def _step(self, send_value: Any = None, throw_err: BaseException | None = None) -> None:
        if self.done():
            return
        if self._cancelled and throw_err is None:
            # cancelled before this step ran: like the reference, a destroyed
            # actor's body never executes past the cancellation point
            if not self._started:
                # never ran at all: close instead of throwing into it so the
                # interpreter doesn't warn about an un-awaited coroutine
                self._coro.close()
                self._set_error(ActorCancelled())
                return
            throw_err = ActorCancelled()
        self._waiting_on = None
        self._started = True
        try:
            if throw_err is not None:
                awaited = self._coro.throw(throw_err)
            else:
                awaited = self._coro.send(send_value)
        except StopIteration as stop:
            self._set(stop.value)
            return
        except ActorCancelled as e:
            self._set_error(e)
            return
        except BaseException as e:  # noqa: BLE001 — error propagates to awaiters
            self._set_error(e)
            return
        if not isinstance(awaited, Future):
            raise TypeError(f"task {self.name} awaited non-Future {awaited!r}")
        self._waiting_on = awaited

        def resume(fut: Future, task=self) -> None:
            # resumption goes through the loop queue at the task's priority:
            # completion order alone never determines execution order
            if fut.exception() is not None:
                task._loop._ready(task._priority, lambda: task._step(throw_err=fut.exception()))
            else:
                task._loop._ready(task._priority, lambda: task._step(send_value=fut.result()))

        self._resume_cb = resume
        awaited.add_done_callback(resume)

    def cancel(self) -> None:
        if self.done():
            return
        self._cancelled = True  # any already-queued _step now throws instead
        if not self._started:
            # never ran: finish it synchronously (no loop turn needed) so the
            # coroutine object is closed, not leaked to the GC
            self._coro.close()
            self._set_error(ActorCancelled())
            return
        if self._waiting_on is not None:
            if self._resume_cb is not None:
                self._waiting_on.remove_done_callback(self._resume_cb)
            self._waiting_on = None
            self._loop._ready(
                self._priority, lambda: self._step(throw_err=ActorCancelled())
            )
        # else: the spawn- or resume-queued _step is already in the heap and
        # will observe _cancelled before running any coroutine code

    def __del__(self) -> None:
        # a task whose loop was discarded before its first step (a cluster
        # handed out a Database — which spawns its metrics emitter — and the
        # test ended without running the loop again) holds a never-started
        # coroutine; close it like cancel-before-start does, instead of
        # leaking a "coroutine was never awaited" warning at GC
        try:
            if not self._started and not self.done():
                self._coro.close()
        except AttributeError:
            pass  # partially-constructed task


class EventLoop:
    """Virtual-clock, priority-ordered, deterministic run loop.

    Event order is a pure function of (seed, program): the ready heap is
    keyed (time, -priority, seq) with seq breaking ties FIFO.  Time is
    virtual; in simulation it jumps instantly to the next timer (Sim2's
    time model), while RealClockDriver (below) maps it onto the wall clock
    for production use — the same seam as INetwork (flow/network.h:192).
    """

    def __init__(self) -> None:
        self._heap: list[tuple[float, int, int, Callable[[], None]]] = []
        self._now = 0.0
        self._seq = 0
        self._stopped = False
        self.tasks_run = 0
        # flow-profiler analog (the reference's --profiler / slow-task
        # sampler): when enabled, wall-clock busy time accumulates per task
        # priority and steps slower than slow_task_threshold are recorded
        self.profile = False
        self.slow_task_threshold = 0.05
        self.busy_s_by_priority: dict[int, float] = {}
        self.slow_tasks: list[tuple[float, int, float]] = []  # (t, pri, dur)
        # Net2 slow-task watch (Net2.actor.cpp checkForSlowTask): when a
        # TraceCollector is bound here, any single callback whose host wall
        # time exceeds slow_task_trace_threshold traces a SEV_WARN SlowTask
        # event — a run-loop stall is invisible to virtual time, so only
        # the wall clock can see it.  Observability only: the measurement
        # never feeds back into scheduling, so determinism holds.
        self.slow_task_trace = None
        self.slow_task_trace_threshold = 0.5

    # -- time --------------------------------------------------------------
    def now(self) -> float:
        return self._now

    def delay(self, seconds: float, priority: int = TaskPriority.DEFAULT_DELAY) -> Future:
        """Future firing `seconds` of virtual time from now (flow delay())."""
        if seconds < 0:
            seconds = 0
        p = Promise()
        self._at(self._now + seconds, priority, lambda: p.send(None) if not p.future.done() else None)
        return p.future

    def yield_(self, priority: int = TaskPriority.DEFAULT_DELAY) -> Future:
        """Reschedule behind same-or-higher-priority ready work (flow yield())."""
        return self.delay(0, priority)

    # -- scheduling --------------------------------------------------------
    def _at(self, when: float, priority: int, fn: Callable[[], None]) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (when, -priority, self._seq, fn))

    def _ready(self, priority: int, fn: Callable[[], None]) -> None:
        self._at(self._now, priority, fn)

    def spawn(
        self,
        coro: Coroutine,
        priority: int = TaskPriority.DEFAULT_ENDPOINT,
        name: str = "",
    ) -> Task:
        task = Task(coro, self, priority, name or getattr(coro, "__name__", "task"))
        self._ready(priority, task._step)
        return task

    # -- running -----------------------------------------------------------
    def run_one(self) -> bool:
        if not self._heap:
            return False
        when, negpri, _seq, fn = heapq.heappop(self._heap)
        if when > self._now:
            self._now = when
        self.tasks_run += 1
        watch = self.slow_task_trace
        if not self.profile and watch is None:
            fn()
            return True
        t0 = _time.perf_counter()
        fn()
        dur = _time.perf_counter() - t0
        pri = -negpri
        if self.profile:
            self.busy_s_by_priority[pri] = self.busy_s_by_priority.get(pri, 0.0) + dur
            if dur >= self.slow_task_threshold and len(self.slow_tasks) < 10_000:
                self.slow_tasks.append((self._now, pri, dur))
        if watch is not None and dur >= self.slow_task_trace_threshold:
            from .trace import SEV_WARN

            watch.trace(
                "SlowTask", severity=SEV_WARN,
                Priority=pri, DurationS=dur,
            )
        return True

    def run_until(self, fut: Future, deadline: float | None = None) -> Any:
        """Drive the loop until `fut` resolves (or virtual deadline)."""
        while not fut.done():
            if deadline is not None and self._now >= deadline:
                raise TimedOut(f"virtual deadline {deadline} reached at {self._now}")
            if not self.run_one():
                raise RuntimeError("deadlock: no runnable tasks but future pending")
        return fut.result()

    def drain(self, max_steps: int = 10_000_000) -> None:
        steps = 0
        while self._heap and steps < max_steps:
            self.run_one()
            steps += 1


class RealClockDriver:
    """Drives an EventLoop against the wall clock — the production twin of
    simulation's instant time jumps (the Net2 side of the Net2/Sim2 seam).

    Virtual time is anchored to a wall-clock origin; the driver sleeps until
    the next timer is due, then lets the loop run everything that is ready.
    Role code awaits the same loop API either way and cannot tell the worlds
    apart.
    """

    def __init__(self, loop: EventLoop) -> None:
        self.loop = loop
        self._origin = _time.monotonic() - loop.now()  # flowlint: ok wall-clock (the real-clock driver IS the wall)

    def run_until(self, fut: Future, wall_timeout: float | None = None) -> Any:
        start = _time.monotonic()  # flowlint: ok wall-clock (wall_timeout is a host bound by contract)
        while not fut.done():
            if wall_timeout is not None and _time.monotonic() - start > wall_timeout:  # flowlint: ok wall-clock (wall_timeout is a host bound by contract)
                raise TimedOut(f"wall timeout {wall_timeout}s")
            if not self.loop._heap:
                raise RuntimeError("deadlock: no runnable tasks but future pending")
            due = self.loop._heap[0][0]
            wall_due = self._origin + due
            delta = wall_due - _time.monotonic()  # flowlint: ok wall-clock (mapping virtual timers onto the wall)
            if delta > 0:
                _time.sleep(min(delta, 0.05))  # flowlint: ok wall-clock (the production sleep-until-due loop)
                continue
            self.loop.run_one()
        return fut.result()


class DeterministicRandom:
    """Seeded RNG behind every random decision (flow/DeterministicRandom.h):
    identical seed ⇒ identical simulation.  Thin, explicit wrapper so call
    sites can't accidentally reach the global `random` module."""

    def __init__(self, seed: int) -> None:
        self._r = _pyrandom.Random(seed)
        self.seed = seed

    def random(self) -> float:
        return self._r.random()

    def random_int(self, lo: int, hi: int) -> int:
        """Uniform in [lo, hi) — half-open like the reference randomInt."""
        return self._r.randrange(lo, hi)

    def random_choice(self, seq):
        return seq[self._r.randrange(len(seq))]

    def random_bytes(self, n: int) -> bytes:
        return self._r.randbytes(n)

    def shuffle(self, seq) -> None:
        self._r.shuffle(seq)

    def coinflip(self, p: float = 0.5) -> bool:
        return self._r.random() < p

    def random_unique_id(self) -> str:
        return f"{self._r.getrandbits(64):016x}"

    def split(self) -> "DeterministicRandom":
        """Child RNG with a derived seed (keeps streams independent)."""
        return DeterministicRandom(self._r.getrandbits(63))
