"""Future combinators — the genericactors.actor.h analog.

wait_all/wait_any/timeout/AsyncVar/AsyncTrigger/quorum/recurring cover the
combinator vocabulary the reference roles are written in
(flow/genericactors.actor.h: waitForAll, quorum, AsyncVar :660,
AsyncTrigger :694, recurring, timeoutError).
"""

from __future__ import annotations

from typing import Any, Awaitable, Callable, Iterable, Sequence

from .core import ActorCancelled, EventLoop, Future, Promise, TaskPriority, TimedOut


def wait_all(futures: Sequence[Future]) -> Future:
    """Resolves with a list of results once every input resolves; fails fast
    on the first error (waitForAll)."""
    out = Promise()
    n = len(futures)
    if n == 0:
        out.send([])
        return out.future
    remaining = [n]

    def on_done(f: Future) -> None:
        if out.future.done():
            return
        if f.exception() is not None:
            out.fail(f.exception())
            return
        remaining[0] -= 1
        if remaining[0] == 0:
            out.send([f.result() for f in futures])

    for f in futures:
        f.add_done_callback(on_done)
    return out.future


def wait_any(futures: Sequence[Future]) -> Future:
    """Resolves with (index, result) of the first to resolve (choose/when)."""
    out = Promise()

    def make_cb(i: int):
        def cb(f: Future) -> None:
            if out.future.done():
                return
            if f.exception() is not None:
                out.fail(f.exception())
            else:
                out.send((i, f.result()))

        return cb

    for i, f in enumerate(futures):
        f.add_done_callback(make_cb(i))
    return out.future


def quorum(futures: Sequence[Future], count: int) -> Future:
    """Resolves once `count` inputs succeed; fails when success becomes
    impossible (flow quorum / smartQuorum)."""
    out = Promise()
    state = {"ok": 0, "err": 0}
    n = len(futures)
    if count > n:
        raise ValueError(f"quorum of {count} impossible with {n} futures")
    if count == 0:
        out.send(None)
        return out.future

    def cb(f: Future) -> None:
        if out.future.done():
            return
        if f.exception() is None:
            state["ok"] += 1
            if state["ok"] >= count:
                out.send(None)
        else:
            state["err"] += 1
            if n - state["err"] < count:
                out.fail(f.exception())

    for f in futures:
        f.add_done_callback(cb)
    return out.future


def timeout_error(loop: EventLoop, fut: Future, seconds: float) -> Future:
    """`fut` or TimedOut after virtual `seconds` (timeoutError)."""
    out = Promise()
    timer = loop.delay(seconds)

    def on_fut(f: Future) -> None:
        if out.future.done():
            return
        if f.exception() is not None:
            out.fail(f.exception())
        else:
            out.send(f.result())

    def on_timer(_f: Future) -> None:
        if not out.future.done():
            out.fail(TimedOut(f"timed out after {seconds}s"))

    fut.add_done_callback(on_fut)
    timer.add_done_callback(on_timer)
    return out.future


class AsyncVar:
    """Observable value: onChange() resolves when set() changes it
    (flow/genericactors.actor.h:660)."""

    def __init__(self, value: Any = None) -> None:
        self._value = value
        self._waiters: list[Promise] = []

    def get(self) -> Any:
        return self._value

    def set(self, value: Any) -> None:
        if value == self._value:
            return
        self._value = value
        waiters, self._waiters = self._waiters, []
        for w in waiters:
            w.send(value)

    def on_change(self) -> Future:
        p = Promise()
        self._waiters.append(p)
        return p.future


class AsyncTrigger:
    """Edge trigger: every waiter outstanding at trigger() time resumes
    (flow/genericactors.actor.h:694)."""

    def __init__(self) -> None:
        self._waiters: list[Promise] = []

    def trigger(self) -> None:
        waiters, self._waiters = self._waiters, []
        for w in waiters:
            w.send(None)

    def on_trigger(self) -> Future:
        p = Promise()
        self._waiters.append(p)
        return p.future


async def recurring(loop: EventLoop, fn: Callable[[], Any], interval: float,
                    priority: int = TaskPriority.DEFAULT_DELAY) -> None:
    """Call fn every `interval` of virtual time forever (flow recurring)."""
    while True:
        await loop.delay(interval, priority)
        fn()


async def broadcast(loop: EventLoop, refs: Sequence, payload: Any,
                    timeout: float = 1.0) -> list:
    """Fire the same request at every ref, gather replies best-effort
    (genericactors broadcast): unreachable peers yield None instead of
    failing the whole fan-out — the pattern behind pings, confirms, and
    registration sweeps."""

    async def one(ref):
        try:
            return await ref.get_reply(payload, timeout=timeout)
        except ActorCancelled:
            raise  # cancellation is not an unreachable peer
        except Exception:  # noqa: BLE001 — best-effort by contract
            return None

    return await wait_all(
        [loop.spawn(one(r), TaskPriority.DEFAULT_ENDPOINT) for r in refs]
    )
