"""Typed tunables registry — the knobs system (flow/Knobs.h:37-48).

Defaults here; simulation may randomize (the reference's
Knobs(randomize=true), fdbserver/Knobs.cpp:33) and anything is overridable
by name, the `--knob_NAME=value` path."""

from __future__ import annotations

from typing import Any


# Environment-variable knobs — the process-level switches that exist
# OUTSIDE the typed Knobs registries below (they gate backend/pipeline
# selection before any cluster object exists, so they ride the
# environment like the reference's command-line --knob_ overrides).
# flowlint's knob-env-sync rule keeps this registry two-way honest: every
# `FDBTPU_*` string literal in the tree must appear here, and every entry
# here must be used somewhere.  KNOBS.md renders this table
# (tools/vexillographer.py).
ENV_KNOBS: dict[str, str] = {
    "FDBTPU_PIPELINE": "opt into the split-phase resolver pipeline "
                       "(conflict/pipeline.py; 0/1, default off)",
    "FDBTPU_PALLAS": "Pallas kernel path selection: auto/compiled/interpret/"
                     "off (conflict/pallas_kernel.py)",
    "FDBTPU_INCREMENTAL": "opt out of the incremental LSM device state "
                          "layout with =0 (conflict/device.py)",
    "FDBTPU_LSM": "recent-window LSM layout override for the device "
                  "backend (conflict/device.py)",
    "FDBTPU_MERGE_IMPL": "device merge/fold implementation override: "
                         "scatter (default) / sort / gather — selects the "
                         "boundary-merge, run-fold and compaction kernels "
                         "(conflict/device.py)",
    "FDBTPU_SEARCH_IMPL": "device search implementation override "
                          "(conflict/device.py)",
    "FDBTPU_REC_ITERS": "fixed-point iteration override for the recurrence "
                        "search fold (conflict/device.py)",
    "FDBTPU_PHASE_TIMING": "=1 populates per-phase kernel wall times with a "
                           "sync per phase (conflict/api.py)",
    "FDBTPU_FORCE_DEGRADE": "=1 boots the DeviceSupervisor directly in "
                            "degraded CPU mode (conflict/supervisor.py)",
    "FDBTPU_SOAK_SEEDS": "seed-matrix width for the chaos sweeps "
                         "(tests/test_chaos_sweep.py; CI default 5)",
    "FDBTPU_SOAK_FORCE_FAIL": "soak triage demo hook: fail this seed after "
                              "its run so the failure carries a full trace "
                              "(tools/soak.py)",
    "FDBTPU_SOAK_DEVICE": "=1 lets a soak campaign's seed subprocesses use "
                          "the device backend (tools/soak.py)",
    "FDBTPU_RESTART_DIR": "restart-image directory override when the caller "
                          "passes none: SaveAndKill part-1 saves land there "
                          "and run_restarting_pair uses it instead of a temp "
                          "dir (workloads/spec.py)",
    "FDBTPU_BLOB_URL": "default backup-container URL for backup_container() "
                       "when the caller names none: file://<prefix>, "
                       "blob://<name>, or http://host:port/<name> against a "
                       "BlobStoreServer (client/backup.py)",
    "FDBTPU_PROTOCOL_VERSION": "override the protocol version this process "
                               "announces in its transport hello (hex or "
                               "decimal int; runtime/serialize.py) — the "
                               "mixed-version upgrade-test hook: an "
                               "\"old\" peer severs cleanly at the hello "
                               "with one traced TransportProtocolMismatch "
                               "(tools/bounce.py)",
}


class Knobs:
    """A bag of typed knobs.  Subclasses declare defaults in __init__ via
    self.init(name, value, randomize=fn) and users override by attribute or
    set_knob(name, string_value)."""

    def __init__(self) -> None:
        self._defs: dict[str, type] = {}

    def init(self, name: str, value: Any) -> None:
        self._defs[name] = type(value)
        setattr(self, name, value)

    def set_knob(self, name: str, value: str) -> None:
        if name not in self._defs:
            raise KeyError(f"no such knob: {name}")
        ty = self._defs[name]
        setattr(self, name, ty(value) if ty is not bool else value in ("1", "true", "True"))

    def names(self) -> list[str]:
        return sorted(self._defs)


class ClientKnobs(Knobs):
    """Client-side tunables — the reference splits knobs into ClientKnobs
    (fdbclient/Knobs.cpp) and ServerKnobs; these govern the NativeAPI
    retry loop and request routing, not any server role."""

    def __init__(self, randomize=None) -> None:
        super().__init__()
        r = randomize
        # on_error retry backoff (reference DEFAULT_BACKOFF/BACKOFF_GROWTH_RATE)
        self.init("DEFAULT_BACKOFF", 0.01 if r is None else 0.005 + r.random() * 0.02)
        self.init("MAX_BACKOFF", 1.0)
        # per-request deadline before the client re-routes / reports
        # TimedOut (covers GRV, reads, watches)
        self.init("REQUEST_TIMEOUT", 5.0)
        # commit deadline: past it the result is UNKNOWN (the fence dance)
        self.init("COMMIT_TIMEOUT", 5.0)
        # pause before re-picking a replica after a dead endpoint
        self.init("REROUTE_DELAY", 0.05)
        # RYW SnapshotCache byte cap per transaction (client/
        # snapshot_cache.py): prior reads at the transaction's read version
        # are kept and re-served locally; past the cap the least-recently-
        # touched known range is evicted (LRU-ish — the newest survivor
        # never is, so an over-cap read still completes consistently)
        self.init("RYW_CACHE_BYTES", 1 << 22)


class CoreKnobs(Knobs):
    def __init__(self, randomize=None) -> None:
        super().__init__()
        r = randomize  # DeterministicRandom or None
        # MVCC window: versions/sec * seconds (reference VERSIONS_PER_SECOND
        # 1e6 and MAX_WRITE_TRANSACTION_LIFE 5.0, fdbserver/Knobs.cpp:30-34;
        # simulation sometimes shrinks the window to 1s to stress TooOld)
        self.init("VERSIONS_PER_SECOND", 1_000_000)
        life = 5.0 if r is None or not r.coinflip(0.25) else 1.0
        self.init("MAX_WRITE_TRANSACTION_LIFE", life)
        self.init("MAX_READ_TRANSACTION_LIFE", life)
        # proxy commit batching (reference COMMIT_TRANSACTION_BATCH_INTERVAL_*)
        self.init("COMMIT_BATCH_INTERVAL_MIN", 0.0005)
        self.init("COMMIT_BATCH_INTERVAL_MAX", 0.010)
        self.init("COMMIT_BATCH_MAX_COUNT", 32768)
        # grv batching
        self.init("GRV_BATCH_INTERVAL", 0.0005)
        # how far version assignment may outrun the newest committed version
        # (reference MAX_VERSIONS_IN_FLIGHT, fdbserver/Knobs.cpp: 100e6) —
        # the sequencer clamps assignment and the proxy's phase-4 throttle
        # parks batches past it
        self.init("MAX_VERSIONS_IN_FLIGHT", 100_000_000)
        # resolver
        self.init("RESOLVER_STATE_MEMORY_LIMIT", 1 << 30)
        # resolutionBalancing (masterserver.actor.cpp:964): poll cadence and
        # the busiest/mean load ratio that triggers a split move
        self.init("RESOLUTION_BALANCE_INTERVAL", 0.5)
        self.init("RESOLUTION_BALANCE_RATIO", 2.0)
        self.init("RESOLUTION_BALANCE_MIN_LOAD", 64)
        # dynamic configuration poll (\xff/conf watcher)
        self.init("CONF_POLL_INTERVAL", 0.5)
        self.init("SAMPLE_OFFSET_PER_KEY", 100)
        # storage
        self.init("STORAGE_DURABILITY_LAG", 0.05)
        self.init("DESIRED_TEAM_SIZE", 3)
        # commit-path retry budget: past this, the proxy reports UNKNOWN and
        # escalates to recovery (longer than FAILURE_TIMEOUT so dead-role
        # heartbeat detection normally wins; this covers proxy-only partitions)
        self.init("COMMIT_PATH_GIVEUP", 4.0)
        # failure detection
        self.init("FAILURE_TIMEOUT", 1.0 if r is None else 0.5 + r.random())
        self.init("HEARTBEAT_INTERVAL", 0.2)
        # ratekeeper
        self.init("TARGET_QUEUE_BYTES", 1 << 27)
        self.init("RATEKEEPER_UPDATE_INTERVAL", 0.25)
        # smoothing time constant for the ratekeeper's per-server model and
        # published budget (reference SMOOTHING_AMOUNT, Knobs.cpp)
        self.init("RATEKEEPER_SMOOTHING_E", 1.0)
        # -- resource-exhaustion plane (docs/OPERATIONS.md "Disk pressure")
        # TLog queue hard limit (reference TLOG_HARD_LIMIT_BYTES): past it
        # the TLog REFUSES commits loudly (SEV_WARN TLogCommitRefused,
        # never a silent ack) instead of growing without bound; ratekeeper
        # e-brakes admission before a healthy cluster ever reaches it —
        # which needs HEADROOM above TARGET_QUEUE_BYTES (1<<27): the
        # spring must have squeezed long before the refusal line
        self.init("TLOG_HARD_LIMIT_BYTES", 1 << 28)
        # storage queue-byte spring (reference TARGET_BYTES_PER_STORAGE_
        # SERVER / STORAGE_HARD_LIMIT_BYTES): smoothed bytes-in-queue per
        # storage server squeeze admission toward the target; crossing the
        # hard limit slams the e-brake
        self.init("TARGET_STORAGE_QUEUE_BYTES", 1 << 26)
        self.init("STORAGE_HARD_LIMIT_BYTES", 1 << 27)
        # free-space limiting (reference storage_server_min_free_space):
        # admission squeezes proportionally once a storage disk's free
        # fraction drops below the target, and the e-brake engages at the
        # minimum — commits stop before the disk physically fills
        self.init("FREE_SPACE_TARGET_FRACTION", 0.25)
        self.init("MIN_FREE_SPACE_FRACTION", 0.05)
        # io_timeout fail-fast (reference io_timeout / MAX_STORAGE_COMMIT_
        # TIME): a disk sync stalled past this many virtual seconds KILLS
        # the owning process through the ordinary kill/recovery machinery
        # rather than wedging the commit plane (storage/files.py)
        self.init("IO_TIMEOUT_S", 5.0)
        # file-level page cache (storage/pagecache.py, the AsyncFileCached
        # analog / reference PAGE_CACHE_4K pool): ONE byte-bounded LRU
        # pool per process lifetime shared by every storage file (B-tree
        # data+header, memory-engine WAL, TLog queue); 0 disables.
        # PAGE_CACHE_4K is the cache page size; READAHEAD_PAGES is how
        # many extra pages a sequential-scan miss fetches in the same
        # pread.  Simulation sometimes shrinks the pool to a few pages so
        # chaos seeds stress eviction/refill instead of an always-hot
        # cache.
        self.init(
            "PAGE_CACHE_BYTES",
            2 << 20 if r is None or not r.coinflip(0.25) else 1 << 14,
        )
        self.init("PAGE_CACHE_4K", 4096)
        self.init("READAHEAD_PAGES", 8)
        # the ssd engine's PARSED-page cache budget (storage/btree.py):
        # decoded pages held above the file-level cache, in approximate
        # heap bytes — byte-bounded so a few huge leaves can't blow the
        # host heap (was a page COUNT blind to page size)
        self.init("BTREE_CACHE_BYTES", 4 << 20)

        # device supervisor (conflict/supervisor.py): the DEFAULT_BACKOFF
        # family applied to the hardware conflict backend.  Every device
        # interaction is bounded by DEVICE_WATCHDOG_S (wall-clock watchdog
        # on the real network; under sim the hang is injected virtually);
        # failed attempts retry with exponential backoff
        # (DEVICE_RETRY_BACKOFF doubling to DEVICE_MAX_BACKOFF), and after
        # DEVICE_RETRY_LIMIT consecutive failures the circuit breaker trips
        # to the CPU reference backend; re-probes then run every
        # DEVICE_REPROBE_INTERVAL seconds until a parity-checked promotion
        # succeeds (docs/OPERATIONS.md "Degraded device backend")
        self.init("DEVICE_WATCHDOG_S", 30.0)
        self.init("DEVICE_RETRY_LIMIT", 3)
        self.init("DEVICE_RETRY_BACKOFF", 0.05 if r is None else 0.02 + r.random() * 0.1)
        self.init("DEVICE_MAX_BACKOFF", 5.0)
        self.init("DEVICE_REPROBE_INTERVAL", 5.0 if r is None else 1.0 + r.random() * 8.0)

        # blob store (storage/blobstore.py): the retrying client's budget.
        # Every operation against the object store retries transient and
        # checksum failures BLOB_RETRY_LIMIT times with exponential backoff
        # from BLOB_BACKOFF_S doubling to BLOB_MAX_BACKOFF_S (each retry
        # traces a SEV_WARN BlobRequestRetried); BLOB_PART_BYTES is the
        # multipart chunk size uploads are split into.
        self.init("BLOB_RETRY_LIMIT", 6)
        self.init("BLOB_BACKOFF_S", 0.02 if r is None else 0.01 + r.random() * 0.05)
        self.init("BLOB_MAX_BACKOFF_S", 1.0)
        self.init("BLOB_PART_BYTES", 1 << 15)

        # trace plane (docs/OBSERVABILITY.md "Distributed tracing"): the
        # TraceEvent file/ring discipline.  TRACE_SEVERITY drops events
        # below it entirely (the reference's --trace severity floor);
        # TRACE_ROLL_SIZE / TRACE_MAX_LOGS bound the rolling per-process
        # trace files (--maxlogssize / --maxlogs analogs); every role
        # emits its rate-converted `*Metrics` event each METRICS_INTERVAL
        # (flow/Stats.h traceCounters cadence)
        self.init("TRACE_SEVERITY", 5)
        self.init("TRACE_ROLL_SIZE", 10 << 20)
        self.init("TRACE_MAX_LOGS", 10)
        self.init("METRICS_INTERVAL", 5.0)
        # Net2 slow-task analog: one run-loop callback exceeding this many
        # HOST WALL seconds traces a SEV_WARN SlowTask event (the stall a
        # virtual clock cannot see — a long jit compile, a blocking
        # syscall).  Soak triage (tools/soak.py) surfaces the per-seed
        # SlowTask count.
        self.init("SLOW_TASK_THRESHOLD", 0.5)

        # process supervisor (tools/fdbmonitor.py; fdbmonitor.cpp restart
        # backoff): a crashed child restarts after MONITOR_RESTART_BACKOFF
        # seconds, doubling per death up to MONITOR_MAX_BACKOFF; a run of
        # MONITOR_BACKOFF_RESET seconds before dying resets the delay (only
        # a crash LOOP escalates).  The conf file is polled for changes
        # every MONITOR_CONF_POLL seconds (SIGHUP forces it), and a stopped
        # child gets MONITOR_KILL_GRACE seconds between SIGTERM and
        # SIGKILL.  The conf's [general] section overrides all five
        # (restart-delay / max-restart-delay / backoff-reset / conf-poll /
        # kill-grace).
        self.init("MONITOR_RESTART_BACKOFF", 0.25)
        self.init("MONITOR_MAX_BACKOFF", 8.0)
        self.init("MONITOR_BACKOFF_RESET", 10.0)
        self.init("MONITOR_CONF_POLL", 0.5)
        self.init("MONITOR_KILL_GRACE", 5.0)

        # commit-plane wire (docs/WIRE.md): transport write coalescing.
        # Queued frames flush once per reactor tick, or immediately once a
        # connection's queue passes WIRE_FLUSH_BYTES (bounds both memory
        # and burst latency); WIRE_COALESCE=false restores flush-per-send.
        self.init("WIRE_COALESCE", True)
        self.init("WIRE_FLUSH_BYTES", 1 << 18)

        # data distribution (DataDistribution.actor.cpp): storage failure
        # ping cadence, shard-size poll cadence, and the split threshold
        # (the reference splits on byte size via StorageMetrics; we count keys)
        # TLog in-memory budget before lagging tags spill payloads to the
        # disk queue (TLogServer spilled-data; TLOG_SPILL_THRESHOLD analog)
        self.init("TLOG_SPILL_BYTES", 1 << 22)

        self.init("DD_PING_INTERVAL", 0.25)
        self.init("DD_SPLIT_INTERVAL", 0.5)
        self.init("DD_SHARD_SPLIT_KEYS", 100_000)
        # StorageMetrics-style split thresholds: shard byte size and
        # committed write bandwidth (reference SHARD_MAX_BYTES +
        # shardSplitter's bandwidth half)
        self.init("DD_SHARD_SPLIT_BYTES", 10_000_000)
        self.init("DD_SHARD_SPLIT_WRITE_BYTES_PER_SEC", 1_000_000)
        # shardMerger (DataDistributionTracker): ADJACENT shards whose
        # combined size is below the merge threshold collapse into one —
        # a fraction of the split point so merge/split cannot oscillate
        self.init("DD_SHARD_MERGE_BYTES", 1_000_000)
        self.init("DD_SHARD_MERGE_KEYS", 10_000)
        # -- load-metric plane (roles/storage_metrics.py; StorageMetrics.
        # actor.h byteSample / bytesReadSample analogs).  The sampling UNIT
        # is the Horvitz-Thompson weight floor: an entry of size sz is
        # sampled with probability min(1, sz/unit), so per-range estimates
        # are unbiased with relative error ~ sqrt(unit / range_bytes).
        # Simulation sometimes shrinks the units so chaos seeds exercise
        # the dense-sample paths too.
        self.init(
            "BYTE_SAMPLE_UNIT",
            512 if r is None or not r.coinflip(0.25) else 32,
        )
        self.init(
            "BANDWIDTH_SAMPLE_UNIT",
            512 if r is None or not r.coinflip(0.25) else 32,
        )
        # bandwidth decay time constant (reference's 2x SMOOTHING_AMOUNT
        # spirit): rate = decayed_weight / tau
        self.init("BANDWIDTH_SMOOTH_SECONDS", 10.0)
        # hot-shard detection + priority relocation (readHotShard analog):
        # a shard whose combined read+write sampled bandwidth exceeds the
        # threshold — and that cannot usefully split — is queued for
        # relocation to the least-loaded team every relocation interval
        self.init("DD_HOT_SHARD_BYTES_PER_KSEC", 50_000_000)
        self.init("DD_HOT_RELOCATION_INTERVAL", 2.0)

    @property
    def mvcc_window_versions(self) -> int:
        return int(self.VERSIONS_PER_SECOND * self.MAX_WRITE_TRANSACTION_LIFE)
