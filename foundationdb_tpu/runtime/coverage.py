"""Rare-path coverage accounting — the coveragetool / TEST() macro analog
(flow/UnitTest.h TEST(); the reference's coveragetool scrapes TEST("...")
sites and simulation asserts they were all hit across a test campaign).

Code marks a rare-but-important path with `testcov("name")`.  Counters are
process-global and cheap (a dict increment); seed-sweep tests assert that
the paths a campaign is supposed to exercise actually fired — the defense
against fault-injection code that silently stops injecting."""

from __future__ import annotations

_hits: dict[str, int] = {}


def testcov(name: str) -> None:
    """Mark a rare-path execution (the TEST("name") macro)."""
    _hits[name] = _hits.get(name, 0) + 1


def hits(name: str) -> int:
    return _hits.get(name, 0)


def all_hits() -> dict[str, int]:
    return dict(_hits)


def reset() -> None:
    _hits.clear()
