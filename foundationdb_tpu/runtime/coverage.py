"""Rare-path coverage accounting — the coveragetool / TEST() macro analog
(flow/UnitTest.h TEST(); the reference's coveragetool scrapes TEST("...")
sites and simulation asserts they were all hit across a test campaign).

Code marks a rare-but-important path with `testcov("name")`.  Counters are
process-global and cheap (a dict increment); seed-sweep tests assert that
the paths a campaign is supposed to exercise actually fired — the defense
against fault-injection code that silently stops injecting.

For campaigns that span OS processes (tools/soak.py), the census leaves
the process through the trace plane: `emit_coverage(trace)` lands one
`CodeCoverage` event per hit name (schema'd in control/status.py
CODE_COVERAGE_SCHEMA) in the run's trace files at sim teardown, and the
soak driver scrapes those — coverage rides the same rolling-JSONL plane
as every other observability signal instead of a side channel."""

from __future__ import annotations

_hits: dict[str, int] = {}


def testcov(name: str) -> None:
    """Mark a rare-path execution (the TEST("name") macro)."""
    _hits[name] = _hits.get(name, 0) + 1


def hits(name: str) -> int:
    return _hits.get(name, 0)


def all_hits() -> dict[str, int]:
    return dict(_hits)


def reset() -> None:
    _hits.clear()


def snapshot() -> dict[str, int]:
    """The current counters, for save/restore around a test (the pytest
    conftest isolates every test's census with this pair)."""
    return dict(_hits)


def restore(snap: dict[str, int]) -> None:
    _hits.clear()
    _hits.update(snap)


def census(baseline: dict[str, int] | None = None) -> dict[str, int]:
    """Hit counts, optionally as the DELTA over a `snapshot()` baseline —
    how one spec run / one campaign seed reports only its own hits when
    the process-global counters carry earlier runs' too."""
    if not baseline:
        return dict(_hits)
    out: dict[str, int] = {}
    for name, n in _hits.items():
        d = n - baseline.get(name, 0)
        if d > 0:
            out[name] = d
    return out


def emit_coverage(trace, baseline: dict[str, int] | None = None) -> None:
    """One `CodeCoverage` trace event per hit name (delta over `baseline`
    when given) — the sim-teardown emission the soak driver's census is
    built from.  A testcov site is 'armed' by definition: it has no
    per-run enable draw, so Armed is always True here (contrast
    buggify.emit_coverage, where armed-but-never-fired is the interesting
    row)."""
    for name, n in sorted(census(baseline).items()):
        trace.trace("CodeCoverage", Name=name, Kind="testcov",
                    Hits=n, Armed=True)
