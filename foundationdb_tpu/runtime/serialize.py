"""Versioned binary serialization — the flow/serialize.h analog.

The reference frames every durable page and wire packet in a versioned
binary archive (`BinaryWriter`/`BinaryReader`, protocol version constant at
flow/serialize.h:188).  This module is the same idea in idiomatic Python:
explicit little-endian codecs (struct), length-prefixed bytes, and a
protocol-version header so future formats can evolve without corrupting old
files.  Disk records (storage/diskqueue.py) and the TCP wire format
(rpc/transport) both build on it.

Deliberately NOT pickle: pickled records are neither versionable nor safe
to read from a half-trusted disk/wire, and their byte layout is not stable
across interpreter versions — determinism (same seed => same bytes) is a
product property here.
"""

from __future__ import annotations

import struct
from typing import Iterable

# protocol version: bump the low byte for compatible additions, high bytes
# for breaking changes (reference currentProtocolVersion 0x0FDB00B061020001)
PROTOCOL_VERSION = 0x0F_DB_70_01


class BinaryWriter:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u8(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<B", v))
        return self

    def u32(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<I", v))
        return self

    def i64(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<q", v))
        return self

    def f64(self, v: float) -> "BinaryWriter":
        self._parts.append(struct.pack("<d", v))
        return self

    def bytes_(self, b: bytes) -> "BinaryWriter":
        self._parts.append(struct.pack("<I", len(b)))
        self._parts.append(b)
        return self

    def str_(self, s: str) -> "BinaryWriter":
        return self.bytes_(s.encode("utf-8"))

    def data(self) -> bytes:
        return b"".join(self._parts)


class BinaryReader:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._buf):
            raise ValueError("truncated record")
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def bytes_(self) -> bytes:
        return self._take(self.u32())

    def str_(self) -> str:
        return self.bytes_().decode("utf-8")

    def eof(self) -> bool:
        return self._pos >= len(self._buf)

    def rest(self) -> bytes:
        """Remaining unread bytes (for nested decoders)."""
        return self._buf[self._pos :]


# ---- mutation / log-entry codecs (shared by TLog + storage engines) -------


def write_mutation(w: BinaryWriter, m) -> None:
    w.u8(int(m.type)).bytes_(m.key).bytes_(m.value if m.value is not None else b"")


def read_mutation(r: BinaryReader):
    from ..roles.types import Mutation, MutationType

    t = MutationType(r.u8())
    return Mutation(t, r.bytes_(), r.bytes_())


def encode_version_mutations(version: int, by_tag: dict[str, list]) -> bytes:
    """One TLog commit record: version + per-tag mutation lists."""
    w = BinaryWriter()
    w.i64(version).u32(len(by_tag))
    for tag, muts in by_tag.items():
        w.str_(tag).u32(len(muts))
        for m in muts:
            write_mutation(w, m)
    return w.data()


def decode_version_mutations(buf: bytes) -> tuple[int, dict[str, list]]:
    r = BinaryReader(buf)
    version = r.i64()
    by_tag: dict[str, list] = {}
    for _ in range(r.u32()):
        tag = r.str_()
        by_tag[tag] = [read_mutation(r) for _ in range(r.u32())]
    return version, by_tag
