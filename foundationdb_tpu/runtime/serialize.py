"""Versioned binary serialization — the flow/serialize.h analog.

The reference frames every durable page and wire packet in a versioned
binary archive (`BinaryWriter`/`BinaryReader`, protocol version constant at
flow/serialize.h:188).  This module is the same idea in idiomatic Python:
explicit little-endian codecs (struct), length-prefixed bytes, and a
protocol-version header so future formats can evolve without corrupting old
files.  Disk records (storage/diskqueue.py) and the TCP wire format
(rpc/transport) both build on it.

Deliberately NOT pickle: pickled records are neither versionable nor safe
to read from a half-trusted disk/wire, and their byte layout is not stable
across interpreter versions — determinism (same seed => same bytes) is a
product property here.
"""

from __future__ import annotations

import os
import pickle
import struct
import time as _time
from itertools import accumulate
from typing import Any, Callable, Iterable

# protocol version: bump the low byte for compatible additions, high bytes
# for breaking changes (reference currentProtocolVersion 0x0FDB00B061020001)
# 0x71: the TCP frame format changed INCOMPATIBLY (pickled tuples -> codec
# frames) — a breaking bump.  The transport stamps this into its hello
# frame and severs a mismatched peer with a traced reason; the TLog's
# durable _R_RESET record, by contrast, kept a legacy decode path.
# ..02: the span-carrying RpcMessage envelope (tag 61, the distributed
# tracing plane).  Low-byte bump: the spanless wire is unchanged, but a
# pre-tracing peer cannot decode sampled traffic, and the EXACT-match
# hello means the pair severs once with a traced TransportProtocolMismatch
# instead of looping on per-message decode failures when sampling turns on.
# ..03: key-selector resolution (tags 53/54, GetKeyRequest/GetKeyReply —
# roles/types.py).  Low-byte bump for the same reason: existing traffic is
# byte-identical, but a pre-selector peer meeting a getKey frame must
# sever once at the hello, not per message.
PROTOCOL_VERSION = 0x0F_DB_71_03


def announced_protocol_version() -> int:
    """The version this process stamps into its transport hello and
    requires of peers.  Normally the build's own PROTOCOL_VERSION; the
    FDBTPU_PROTOCOL_VERSION env override exists so upgrade tests
    (tools/bounce.py's mixed-version bounce) can boot a genuinely
    "old" OS process and watch the pair sever cleanly at the hello —
    read once at process start, like every launch-time env knob."""
    raw = os.environ.get("FDBTPU_PROTOCOL_VERSION")
    if not raw:
        return PROTOCOL_VERSION
    return int(raw, 16) if raw.lower().startswith("0x") else int(raw)


class BinaryWriter:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u8(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<B", v))
        return self

    def u32(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<I", v))
        return self

    def i64(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<q", v))
        return self

    def f64(self, v: float) -> "BinaryWriter":
        self._parts.append(struct.pack("<d", v))
        return self

    def bytes_(self, b: bytes) -> "BinaryWriter":
        self._parts.append(struct.pack("<I", len(b)))
        self._parts.append(b)
        return self

    def str_(self, s: str) -> "BinaryWriter":
        return self.bytes_(s.encode("utf-8"))

    def data(self) -> bytes:
        return b"".join(self._parts)


class BinaryReader:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._buf):
            raise ValueError("truncated record")
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def bytes_(self) -> bytes:
        return self._take(self.u32())

    def str_(self) -> str:
        return self.bytes_().decode("utf-8")

    def eof(self) -> bool:
        return self._pos >= len(self._buf)

    def rest(self) -> bytes:
        """Remaining unread bytes (for nested decoders)."""
        return self._buf[self._pos :]


# ---- mutation / log-entry codecs (shared by TLog + storage engines) -------


def write_mutation(w: BinaryWriter, m) -> None:
    w.u8(int(m.type)).bytes_(m.key).bytes_(m.value if m.value is not None else b"")


def read_mutation(r: BinaryReader):
    from ..roles.types import Mutation, MutationType

    t = MutationType(r.u8())
    return Mutation(t, r.bytes_(), r.bytes_())


def encode_version_mutations(version: int, by_tag: dict[str, list]) -> bytes:
    """One TLog commit record: version + per-tag mutation lists."""
    w = BinaryWriter()
    w.i64(version).u32(len(by_tag))
    for tag, muts in by_tag.items():
        w.str_(tag).u32(len(muts))
        for m in muts:
            write_mutation(w, m)
    return w.data()


def decode_version_mutations(buf: bytes) -> tuple[int, dict[str, list]]:
    r = BinaryReader(buf)
    version = r.i64()
    by_tag: dict[str, list] = {}
    for _ in range(r.u32()):
        tag = r.str_()
        by_tag[tag] = [read_mutation(r) for _ in range(r.u32())]
    return version, by_tag


# ===========================================================================
# Tag-dispatched wire codec registry (the commit-plane wire tentpole).
#
# The reference serializes every wire packet through versioned binary
# writers (flow/serialize.h:188's BinaryWriter + ObjectSerializer); our
# transport used to pickle every frame instead — flagged in VERDICT.md
# both as a perf sink (pickling a 10K-txn resolver batch per hop) and as
# the wire's trust problem (unpickling hands a peer code execution).
#
# This registry is the migration path: message types register a (tag,
# encode, decode) triple; `encode_payload` dispatches on EXACT type and
# emits `u16 tag + body`; unregistered payloads keep the pickle path
# under TAG_PICKLE, counted per type in WireStats so a hot message
# regressing onto the fallback is visible by name.  Both the real TCP
# transport (rpc/transport.py, including its loopback fast path) and the
# simulated fabric (rpc/network.py) dispatch through here, so every
# seeded simulation exercises the exact encoders production runs on.
#
# Hot-message codecs use a struct-of-arrays layout (all counts, then all
# lengths, then one key-bytes blob) so the Python-level work per element
# is a couple of list appends — measured ~2x faster than protocol-4
# pickle on a bench-class resolver batch, where a naive field-by-field
# writer loses to pickle's C loop (tests/test_codecs.py pins the margin).
# ===========================================================================

_ST_I = struct.Struct("<I")
_ST_H = struct.Struct("<H")
_ST_q = struct.Struct("<q")
_ST_qqI = struct.Struct("<qqI")
_ST_qII = struct.Struct("<qII")

# reserved scalar tags (0-15); registered message codecs start at 16
TAG_PICKLE = 0
TAG_NONE = 1
TAG_INT = 2
TAG_BYTES = 3
TAG_STR = 4
TAG_TRUE = 5
TAG_FALSE = 6

_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


class CodecError(ValueError):
    """A corrupt, truncated, or unknown-tag codec frame.  The transport
    treats this exactly like an undeserializable pickle frame: count it
    and sever the connection before anything reaches a role."""


class Unencodable(TypeError):
    """Raised under strict encoding when a payload (or anything nested in
    it) has no registered codec — the caller wants to know, not to get a
    silent pickle frame (SimNetwork uses this to fall back to deepcopy)."""


_ENC_BY_TYPE: dict[type, tuple[int, Callable, Callable]] = {}
_DEC_BY_TAG: dict[int, Callable] = {}
_ensured = False


def register_codec(tag: int, cls: type, enc: Callable, dec: Callable) -> None:
    """Register `cls` under `tag`.  `enc(obj, stats, strict) -> bytes`
    produces the body; `dec(buf, stats) -> obj` parses it (raising any
    ValueError/struct.error/IndexError on corruption — decode_payload
    normalizes those to CodecError).  Dispatch is on EXACT type: a
    subclass of a registered message falls back to pickle rather than
    silently truncating its extra state.

    An encoder may instead return `(tag, bytes)` to pick between layouts
    for the same type — the zero-cost-optional-field pattern: RpcMessage
    keeps its spanless layout byte-identical under this tag and routes
    span-carrying envelopes to a `register_decoder` tag."""
    if tag < 16:
        raise ValueError(f"tags 0-15 are reserved (got {tag})")
    prev = _ENC_BY_TYPE.get(cls)
    if prev is not None and prev[0] != tag:
        raise ValueError(f"{cls.__name__} already registered under {prev[0]}")
    if tag in _DEC_BY_TAG and (prev is None or prev[0] != tag):
        raise ValueError(f"tag {tag} already in use")
    _ENC_BY_TYPE[cls] = (tag, enc, dec)
    _DEC_BY_TAG[tag] = dec


def register_decoder(tag: int, dec: Callable) -> None:
    """Register a decode-only tag: the alternate-layout half of an encoder
    that returns `(tag, body)` (see register_codec)."""
    if tag < 16:
        raise ValueError(f"tags 0-15 are reserved (got {tag})")
    if tag in _DEC_BY_TAG:
        raise ValueError(f"tag {tag} already in use")
    _DEC_BY_TAG[tag] = dec


def register_empty_codec(tag: int, cls: type) -> None:
    """Register a no-field message (the many `...Request` markers)."""
    register_codec(tag, cls, lambda o, st, strict: b"", lambda b, st: cls())


def registered_types() -> dict[type, int]:
    """type -> tag of every registered codec (test/verification surface)."""
    _ensure_codecs()
    return {cls: tag for cls, (tag, _e, _d) in _ENC_BY_TYPE.items()}


def _ensure_codecs() -> None:
    """Codecs register at module import of the types they serve.  Encoding
    never needs this (holding an instance implies its module is loaded),
    but a decoder can meet a tag before this process imported the serving
    module — import the known registrars once, lazily (they live above
    this module in the layering, hence the local imports)."""
    global _ensured
    if _ensured:
        return
    _ensured = True
    from ..rpc import stream as _stream  # noqa: F401  (RpcMessage)
    from ..roles import types as _types  # noqa: F401  (role messages)


def encode_any(obj: Any, stats=None, strict: bool = False) -> tuple[int, bytes]:
    """(tag, body) for any payload; pickle fallback unless `strict`.

    A registered encoder that RAISES (a malformed instance — e.g. a test
    handing a message non-canonical field contents) downgrades to the
    fallback rather than killing the send path: under strict that means
    Unencodable (the sim deep-copies instead), otherwise a counted pickle
    frame — visible in WireStats.fallback_types, never a crash."""
    t = type(obj)
    entry = _ENC_BY_TYPE.get(t)
    if entry is not None:
        tag, enc, _dec = entry
        try:
            out = enc(obj, stats, strict)
            # an encoder may pick an alternate layout by returning its own
            # (tag, body) — the optional-field pattern (register_codec doc)
            return out if type(out) is tuple else (tag, out)
        except Exception as e:  # noqa: BLE001 — downgrade, don't crash sends
            if strict:
                raise e if isinstance(e, Unencodable) else Unencodable(repr(e))
            if stats is not None:
                stats.note_fallback(obj)
            return TAG_PICKLE, pickle.dumps(obj, protocol=4)
    if obj is None:
        return TAG_NONE, b""
    if t is int and _I64_MIN <= obj <= _I64_MAX:
        return TAG_INT, _ST_q.pack(obj)
    if t is bytes:
        return TAG_BYTES, obj
    if t is str:
        return TAG_STR, obj.encode("utf-8")
    if t is bool:
        return (TAG_TRUE, b"") if obj else (TAG_FALSE, b"")
    if strict:
        raise Unencodable(t.__name__)
    if stats is not None:
        stats.note_fallback(obj)
    return TAG_PICKLE, pickle.dumps(obj, protocol=4)


def decode_any(tag: int, buf: bytes, stats=None) -> Any:
    dec = _DEC_BY_TAG.get(tag)
    if dec is not None:
        return dec(buf, stats)
    if tag == TAG_NONE:
        return None
    if tag == TAG_INT:
        return _ST_q.unpack(buf)[0]
    if tag == TAG_BYTES:
        return buf
    if tag == TAG_STR:
        return buf.decode("utf-8")
    if tag == TAG_TRUE:
        return True
    if tag == TAG_FALSE:
        return False
    if tag == TAG_PICKLE:
        if stats is not None:
            stats.decode_fallbacks += 1
        return pickle.loads(buf)
    _ensure_codecs()
    dec = _DEC_BY_TAG.get(tag)
    if dec is None:
        raise CodecError(f"unknown codec tag {tag}")
    return dec(buf, stats)


def encode_payload(payload: Any, stats=None, strict: bool = False) -> bytes:
    """`u16 tag + body` for one payload (the loopback/sim unit)."""
    t0 = _time.perf_counter()
    tag, body = encode_any(payload, stats, strict)
    blob = _ST_H.pack(tag) + body
    if stats is not None:
        stats.frames_encoded += 1
        stats.bytes_encoded += len(blob)
        stats.encode_s += _time.perf_counter() - t0
    return blob


def decode_payload(buf: bytes, stats=None) -> Any:
    t0 = _time.perf_counter()
    try:
        if len(buf) < 2:
            raise CodecError("short payload")
        out = decode_any(_ST_H.unpack_from(buf, 0)[0], buf[2:], stats)
    except CodecError:
        raise
    except (ValueError, struct.error, IndexError, KeyError,
            UnicodeDecodeError, EOFError, pickle.UnpicklingError) as e:
        raise CodecError(f"corrupt payload: {e!r}") from e
    if stats is not None:
        stats.frames_decoded += 1
        stats.bytes_decoded += len(buf)
        stats.decode_s += _time.perf_counter() - t0
    return out


# ---- wire frames (rpc/transport.py) ---------------------------------------
#
# frame := token(u32 len + utf8) + addr(u8 flag [+ u32 iplen + ip + u32
# port]) + payload(u16 tag + body).  The whole frame is binary; only the
# payload *body* may be a pickle blob (TAG_PICKLE, cold control traffic).


def write_addr(parts: list, addr) -> None:
    """THE address framing (u8 flag [+ u32 iplen + ip + u32 port]) —
    shared by the frame header and the RpcMessage reply endpoint so the
    two can never drift."""
    if addr is None:
        parts.append(b"\x00")
    else:
        ip = addr.ip.encode("utf-8")
        parts.append(b"\x01")
        parts.append(_ST_I.pack(len(ip)))
        parts.append(ip)
        parts.append(_ST_I.pack(addr.port))


def read_addr(buf: bytes, pos: int) -> tuple[Any, int]:
    flag = buf[pos]
    pos += 1
    if flag == 0:
        return None, pos
    if flag != 1:
        raise CodecError(f"bad addr flag {flag}")
    (nip,) = _ST_I.unpack_from(buf, pos)
    pos += 4
    ip = buf[pos : pos + nip].decode("utf-8")
    pos += nip
    (port,) = _ST_I.unpack_from(buf, pos)
    from ..rpc.network import NetworkAddress

    return NetworkAddress(ip, port), pos + 4


def encode_frame(token: str, addr, payload: Any, stats=None) -> bytes:
    t0 = _time.perf_counter()
    tok = token.encode("utf-8")
    parts = [_ST_I.pack(len(tok)), tok]
    write_addr(parts, addr)
    tag, body = encode_any(payload, stats)
    parts.append(_ST_H.pack(tag))
    parts.append(body)
    blob = b"".join(parts)
    if stats is not None:
        stats.frames_encoded += 1
        stats.bytes_encoded += len(blob)
        stats.encode_s += _time.perf_counter() - t0
    return blob


def decode_frame(buf: bytes, stats=None) -> tuple[str, Any, Any]:
    """(token, addr | None, payload); CodecError on any corruption."""
    t0 = _time.perf_counter()
    try:
        (ntok,) = _ST_I.unpack_from(buf, 0)
        pos = 4 + ntok
        token = buf[4:pos].decode("utf-8")
        if len(buf) < pos + 1:
            raise CodecError("truncated frame header")
        addr, pos = read_addr(buf, pos)
        (tag,) = _ST_H.unpack_from(buf, pos)
        payload = decode_any(tag, buf[pos + 2 :], stats)
    except CodecError:
        raise
    except (ValueError, struct.error, IndexError, KeyError,
            UnicodeDecodeError, EOFError, pickle.UnpicklingError) as e:
        raise CodecError(f"corrupt frame: {e!r}") from e
    if stats is not None:
        stats.frames_decoded += 1
        stats.bytes_decoded += len(buf)
        stats.decode_s += _time.perf_counter() - t0
    return token, addr, payload


# ---- struct-of-arrays helpers for the hot batch codecs --------------------
#
# One length array + one joined blob instead of per-key length prefixes:
# the per-element Python work collapses to list appends on encode and,
# on decode, C-level `map(buf.__getitem__, map(slice, ...))` slicing.


def soa_encode_keys(lens: list[int], keys: list[bytes]) -> bytes:
    nk = len(lens)
    return struct.pack(f"<I{nk}I", nk, *lens) + b"".join(keys)


def soa_decode_keys(buf: bytes, pos: int) -> tuple[list[bytes], int]:
    """Parse `u32 nk + nk*u32 lens + blob` at `pos`; returns (keys, end)."""
    (nk,) = _ST_I.unpack_from(buf, pos)
    pos += 4
    lens = struct.unpack_from(f"<{nk}I", buf, pos)
    pos += 4 * nk
    offs = list(accumulate(lens, initial=pos))
    end = offs[-1]
    if end > len(buf):
        raise CodecError("truncated key blob")
    keys = list(map(buf.__getitem__, map(slice, offs, offs[1:])))
    return keys, end
