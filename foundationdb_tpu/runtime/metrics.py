"""Metrics core: exponential smoothing + percentile sampling
(flow/Smoother.h Smoother/TimerSmoother; flow/ContinuousSample.h) — the
time-series primitives the ratekeeper, load balancer, and perf workloads
build on (flow/Stats.h counters live in runtime/trace.py)."""

from __future__ import annotations

import math
from typing import Callable


class Smoother:
    """Exponentially-smoothed total: `smooth_total` chases the true total
    with time constant `e_time`, and `smooth_rate` is the smoothed
    d(total)/dt — the reference's Smoother, used for rates and latencies
    that must not whipsaw the control loops reading them."""

    def __init__(self, e_time: float, clock: Callable[[], float]) -> None:
        self.e_time = e_time
        self._clock = clock
        self._time = clock()
        self._total = 0.0
        self._estimate = 0.0

    def reset(self, value: float) -> None:
        self._total = value
        self._estimate = value
        self._time = self._clock()

    def set_total(self, value: float) -> None:
        self._advance()
        self._total = value

    def add_delta(self, delta: float) -> None:
        self._advance()
        self._total += delta

    def _advance(self) -> None:
        now = self._clock()
        dt = now - self._time
        if dt > 0:
            self._estimate += (self._total - self._estimate) * (
                1 - math.exp(-dt / self.e_time)
            )
            self._time = now

    def smooth_total(self) -> float:
        self._advance()
        return self._estimate

    def smooth_rate(self) -> float:
        """Smoothed rate of change: (total - estimate) / e_time — exact for
        a constant-rate input, lagging for steps (by design)."""
        self._advance()
        return (self._total - self._estimate) / self.e_time


class ContinuousSample:
    """Fixed-size uniform reservoir over a stream, with percentile reads
    (flow/ContinuousSample.h): every element ever added has equal
    probability of being in the sample, so percentiles track the whole
    stream, not a recent window."""

    def __init__(self, size: int, rng=None) -> None:
        self._size = size
        self._rng = rng
        self._samples: list[float] = []
        self._n = 0
        self._sorted = True

    def add(self, value: float) -> None:
        self._n += 1
        if len(self._samples) < self._size:
            self._samples.append(value)
            self._sorted = False
        else:
            if self._rng is not None:
                j = self._rng.random_int(0, self._n)
            else:
                # private xorshift, NOT the global random module: sampling
                # must never make a seeded simulation replay differently
                self._x = (getattr(self, "_x", 0x9E3779B9) * 0x2545F491) & 0xFFFFFFFF
                self._x ^= self._x >> 13
                j = self._x % self._n
            if j < self._size:
                self._samples[j] = value
                self._sorted = False

    @property
    def count(self) -> int:
        return self._n

    def percentile(self, p: float) -> float:
        if not self._samples:
            return 0.0
        if not self._sorted:
            self._samples.sort()
            self._sorted = True
        idx = min(int(p * len(self._samples)), len(self._samples) - 1)
        return self._samples[idx]

    def median(self) -> float:
        return self.percentile(0.5)
