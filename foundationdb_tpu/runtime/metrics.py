"""Metrics core: exponential smoothing + percentile sampling
(flow/Smoother.h Smoother/TimerSmoother; flow/ContinuousSample.h) — the
time-series primitives the ratekeeper, load balancer, and perf workloads
build on (flow/Stats.h counters live in runtime/trace.py)."""

from __future__ import annotations

import math
from typing import Callable


class Smoother:
    """Exponentially-smoothed total: `smooth_total` chases the true total
    with time constant `e_time`, and `smooth_rate` is the smoothed
    d(total)/dt — the reference's Smoother, used for rates and latencies
    that must not whipsaw the control loops reading them."""

    def __init__(self, e_time: float, clock: Callable[[], float]) -> None:
        self.e_time = e_time
        self._clock = clock
        self._time = clock()
        self._total = 0.0
        self._estimate = 0.0

    def reset(self, value: float) -> None:
        self._total = value
        self._estimate = value
        self._time = self._clock()

    def set_total(self, value: float) -> None:
        self._advance()
        self._total = value

    def add_delta(self, delta: float) -> None:
        self._advance()
        self._total += delta

    def _advance(self) -> None:
        now = self._clock()
        dt = now - self._time
        if dt > 0:
            self._estimate += (self._total - self._estimate) * (
                1 - math.exp(-dt / self.e_time)
            )
            self._time = now

    def smooth_total(self) -> float:
        self._advance()
        return self._estimate

    def smooth_rate(self) -> float:
        """Smoothed rate of change: (total - estimate) / e_time — exact for
        a constant-rate input, lagging for steps (by design)."""
        self._advance()
        return (self._total - self._estimate) / self.e_time


# SLO-facing latency thresholds (seconds): the flow/Stats.h LatencyBands
# defaults the reference wires into GRV/commit/read stats — operators alert
# on band counts, Ratekeeper reasons about the tail bands.
DEFAULT_LATENCY_BANDS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


class LatencyBands:
    """Counts per latency threshold bucket (flow/Stats.h:155 LatencyBands).

    Buckets are DISJOINT — measurement m lands in the first band with
    m < threshold, or the overflow band — so the bucket counts always sum
    to the total number of operations (the invariant status consumers
    check).  The reference keeps cumulative <=threshold counters; disjoint
    buckets carry the same information and sum cleanly across roles."""

    def __init__(self, thresholds: tuple[float, ...] = DEFAULT_LATENCY_BANDS) -> None:
        self.thresholds = tuple(thresholds)
        self.counts = [0] * (len(self.thresholds) + 1)

    def add(self, latency: float) -> None:
        for i, t in enumerate(self.thresholds):
            if latency < t:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    @property
    def count(self) -> int:
        return sum(self.counts)

    def snapshot(self) -> dict:
        bands = {f"<{t:g}": c for t, c in zip(self.thresholds, self.counts)}
        bands[f">={self.thresholds[-1]:g}"] = self.counts[-1]
        return {"count": self.count, "bands": bands}


class LatencyTracker:
    """One pipeline stage's latency model: SLO bands + a uniform reservoir
    for percentiles + sum/max — the LatencyBands-plus-ContinuousSample pair
    every instrumented station in the commit/GRV/read paths owns."""

    def __init__(
        self,
        thresholds: tuple[float, ...] = DEFAULT_LATENCY_BANDS,
        sample_size: int = 500,
    ) -> None:
        self.bands = LatencyBands(thresholds)
        self.sample = ContinuousSample(sample_size)
        self.sum = 0.0
        self.max = 0.0

    def observe(self, latency: float) -> None:
        self.bands.add(latency)
        self.sample.add(latency)
        self.sum += latency
        if latency > self.max:
            self.max = latency

    @property
    def count(self) -> int:
        return self.bands.count

    def snapshot(self) -> dict:
        n = self.count
        return {
            "count": n,
            "mean": self.sum / n if n else 0.0,
            "max": self.max,
            "p50": self.sample.percentile(0.5),
            "p95": self.sample.percentile(0.95),
            "p99": self.sample.percentile(0.99),
            "bands": self.bands.snapshot()["bands"],
        }

    @classmethod
    def merged(cls, trackers: "list[LatencyTracker]") -> dict:
        """One snapshot over several trackers (e.g. the same stage across
        all proxies): counts and bands sum, percentiles come from the
        pooled reservoirs — the merge the status roll-up needs, done on
        the tracker objects because percentiles cannot be merged from
        finished snapshots.

        Reservoirs are fixed-size, so each sample is WEIGHTED by how many
        observations it represents (t.count / len(samples)): a proxy that
        served 100k commits must not be averaged 50/50 against one that
        served 500, or the merged p50 reads like the idle proxy."""
        out = cls()
        bands: dict[str, int] = {}
        weighted: list[tuple[float, float]] = []
        n = 0
        for t in trackers:
            n += t.count
            out.sum += t.sum
            out.max = max(out.max, t.max)
            for k, v in t.bands.snapshot()["bands"].items():
                bands[k] = bands.get(k, 0) + v
            if t.sample._samples:
                w = t.count / len(t.sample._samples)
                weighted.extend((v, w) for v in t.sample._samples)
        weighted.sort()
        total_w = sum(w for _v, w in weighted)

        def pct(p: float) -> float:
            if not weighted:
                return 0.0
            target = p * total_w
            acc = 0.0
            for v, w in weighted:
                acc += w
                if acc >= target:
                    return v
            return weighted[-1][0]

        return {
            "count": n,
            "mean": out.sum / n if n else 0.0,
            "max": out.max,
            "p50": pct(0.5),
            "p95": pct(0.95),
            "p99": pct(0.99),
            "bands": bands,
        }


class WireStats:
    """Commit-plane wire counters (the tentpole's observability surface —
    docs/WIRE.md): bytes and wall time through the codec registry, frames
    per transport flush, and — the trust/coverage signal — how often a
    payload fell back to pickle (by type, so a hot message regressing onto
    the fallback path is visible by name, not just as a count).

    Wall times are host-measured (time.perf_counter) and observability
    only: they never feed back into simulation behavior, exactly like
    KernelStats, so determinism is unaffected."""

    __slots__ = (
        "frames_encoded", "frames_decoded", "bytes_encoded", "bytes_decoded",
        "encode_s", "decode_s", "pickle_fallbacks", "fallback_types",
        "flushes", "frames_flushed", "decode_fallbacks",
    )

    def __init__(self) -> None:
        self.frames_encoded = 0
        self.frames_decoded = 0
        self.bytes_encoded = 0
        self.bytes_decoded = 0
        self.encode_s = 0.0
        self.decode_s = 0.0
        self.pickle_fallbacks = 0          # encode-side payloads that left the
        self.fallback_types: dict[str, int] = {}  # codec registry (by type)
        self.decode_fallbacks = 0          # tag-0 frames decoded
        self.flushes = 0                   # transport write coalescing
        self.frames_flushed = 0

    def note_fallback(self, obj) -> None:
        self.pickle_fallbacks += 1
        name = type(obj).__name__
        self.fallback_types[name] = self.fallback_types.get(name, 0) + 1

    def snapshot(self) -> dict:
        return {
            "frames_encoded": self.frames_encoded,
            "frames_decoded": self.frames_decoded,
            "bytes_encoded": self.bytes_encoded,
            "bytes_decoded": self.bytes_decoded,
            "encode_ms": self.encode_s * 1e3,
            "decode_ms": self.decode_s * 1e3,
            "pickle_fallbacks": self.pickle_fallbacks,
            "fallback_types": dict(self.fallback_types),
            "decode_fallbacks": self.decode_fallbacks,
            "flushes": self.flushes,
            "frames_flushed": self.frames_flushed,
            "frames_per_flush": (
                self.frames_flushed / self.flushes if self.flushes else 0.0
            ),
        }


class ContinuousSample:
    """Fixed-size uniform reservoir over a stream, with percentile reads
    (flow/ContinuousSample.h): every element ever added has equal
    probability of being in the sample, so percentiles track the whole
    stream, not a recent window."""

    def __init__(self, size: int, rng=None) -> None:
        self._size = size
        self._rng = rng
        self._samples: list[float] = []
        self._n = 0
        self._sorted = True

    def add(self, value: float) -> None:
        self._n += 1
        if len(self._samples) < self._size:
            self._samples.append(value)
            self._sorted = False
        else:
            if self._rng is not None:
                j = self._rng.random_int(0, self._n)
            else:
                # private xorshift, NOT the global random module: sampling
                # must never make a seeded simulation replay differently
                self._x = (getattr(self, "_x", 0x9E3779B9) * 0x2545F491) & 0xFFFFFFFF
                self._x ^= self._x >> 13
                j = self._x % self._n
            if j < self._size:
                self._samples[j] = value
                self._sorted = False

    @property
    def count(self) -> int:
        return self._n

    def percentile(self, p: float) -> float:
        if not self._samples:
            return 0.0
        if not self._sorted:
            self._samples.sort()
            self._sorted = True
        idx = min(int(p * len(self._samples)), len(self._samples) - 1)
        return self._samples[idx]

    def median(self) -> float:
        return self.percentile(0.5)
