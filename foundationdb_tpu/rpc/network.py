"""Simulated network fabric — the Sim2 analog (fdbrpc/sim2.actor.cpp:714).

The reference's deepest architectural property is that the transport is a
seam: Net2 (real TCP) and Sim2 (simulated, deterministic) implement the same
INetwork, so whole clusters run in one seeded process.  This module is that
simulated world for the Python control plane: `SimNetwork` owns simulated
processes, delivers endpoint-addressed messages with seeded latency, and
injects faults — clogging (sim2 SimClogging :108, clogPair :1477),
partitions, process kills/reboots (fdbrpc/simulator.h:148-153).

Messages cross a serialization boundary at send time: payloads with a
registered wire codec (runtime/serialize.py, docs/WIRE.md) round-trip
through the SAME binary encoders the real TCP transport uses — so every
seeded simulation, chaos sweep, and serializability test exercises the
production wire format — and anything else is deep-copied (counted as a
codec fallback in `wire`).  Either way a simulated process can never share
mutable state with a peer, the same isolation the wire gives the reference.

The RPC vocabulary (RequestStream/ReplyPromise, fdbrpc/fdbrpc.h:217) lives
in rpc/stream.py on top of this fabric; roles only see that typed layer, so
a future real-TCP fabric slots in under them unchanged.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, Callable

from ..runtime.core import (
    BrokenPromise,
    DeterministicRandom,
    EventLoop,
    Future,
    Promise,
    TaskPriority,
)
from ..runtime.metrics import WireStats
from ..runtime.serialize import Unencodable, decode_payload, encode_payload
from ..runtime.trace import TraceCollector


@dataclasses.dataclass(frozen=True, order=True)
class NetworkAddress:
    ip: str
    port: int

    def __str__(self) -> str:
        return f"{self.ip}:{self.port}"


@dataclasses.dataclass(frozen=True)
class Endpoint:
    """(address, token): the reference's routing pair (FlowTransport.h:34)."""

    address: NetworkAddress
    token: str


class EndpointTable:
    """Token -> handler table shared by the simulated and real process
    objects (the FlowTransport endpoint map).  Delivery to a dead process or
    an unknown token is dropped, like the reference's unknown-endpoint path."""

    def __init__(self, address: NetworkAddress, name: str) -> None:
        self.address = address
        self.name = name
        self.alive = True
        self._endpoints: dict[str, Callable[[Any], None]] = {}

    def register(self, token: str, handler: Callable[[Any], None]) -> Endpoint:
        self._endpoints[token] = handler
        return Endpoint(self.address, token)

    def unregister(self, token: str) -> None:
        self._endpoints.pop(token, None)

    def _deliver(self, token: str, payload: Any) -> None:
        if not self.alive:
            return
        handler = self._endpoints.get(token)
        if handler is not None:
            handler(payload)


class SimProcess(EndpointTable):
    """A simulated process: endpoint table + lifecycle (ISimulator::ProcessInfo).
    `machine`/`dc` are locality labels (ISimulator machine/data-hall model,
    fdbrpc/sim2.actor.cpp:714): killing a machine kills every process on it."""

    def __init__(self, net: "SimNetwork", address: NetworkAddress, name: str,
                 machine: str | None = None, dc: str | None = None) -> None:
        super().__init__(address, name)
        self.net = net
        self.machine = machine
        self.dc = dc
        self.reboots = 0
        self.on_death: list[Promise] = []

    def new_token(self) -> str:
        return self.net.rng.random_unique_id()

    def kill(self) -> None:
        """Hard kill: endpoints vanish, in-flight replies break."""
        self.alive = False
        self._endpoints.clear()
        deaths, self.on_death = self.on_death, []
        for p in deaths:
            if not p.future.done():
                p.send(None)

    def reboot(self) -> None:
        """Kill then come back empty: roles must re-register (the worker
        restores its roles on reboot — fdbserver/worker.actor.cpp:577)."""
        self.kill()
        self.alive = True
        self.reboots += 1


class SimNetwork:
    """Deterministic message fabric over an EventLoop.

    Latency: seeded uniform in [min_latency, max_latency).  Faults:
      clog_pair(a, b, t)    delay a->b messages until now+t
      partition(a, b)       drop a<->b messages until healed
      kill/reboot           via SimProcess
    Delivery order between a pair is preserved (FIFO per (src, dst) like a
    TCP connection): each pair's messages are chained behind the previous
    delivery time.
    """

    def __init__(
        self,
        loop: EventLoop,
        rng: DeterministicRandom,
        trace: TraceCollector | None = None,
        min_latency: float = 0.0001,
        max_latency: float = 0.002,
    ) -> None:
        self.loop = loop
        self.rng = rng.split()
        self.trace = trace or TraceCollector(clock=loop.now)
        self.min_latency = min_latency
        self.max_latency = max_latency
        self.processes: dict[NetworkAddress, SimProcess] = {}
        self._clogged_until: dict[tuple[NetworkAddress, NetworkAddress], float] = {}
        self._partitioned: set[frozenset[NetworkAddress]] = set()
        self._pair_clock: dict[tuple[NetworkAddress, NetworkAddress], float] = {}
        self.messages_sent = 0
        self.messages_dropped = 0
        self.wire = WireStats()  # codec counters (same surface as RealNetwork)

    # -- topology ----------------------------------------------------------
    def create_process(self, name: str, ip: str | None = None, port: int = 4500,
                       machine: str | None = None, dc: str | None = None) -> SimProcess:
        if ip is None:
            ip = f"1.0.0.{len(self.processes) + 1}"
        addr = NetworkAddress(ip, port)
        if addr in self.processes:
            raise ValueError(f"address {addr} in use")
        proc = SimProcess(self, addr, name, machine=machine, dc=dc)
        self.processes[addr] = proc
        return proc

    def machine_processes(self, machine: str) -> list[SimProcess]:
        return [p for p in self.processes.values() if p.machine == machine]

    def kill_machine(self, machine: str) -> list[SimProcess]:
        """Correlated failure: every process on the machine dies at once
        (the reference's machine kills, sim2.actor.cpp killMachine)."""
        victims = [p for p in self.machine_processes(machine) if p.alive]
        for p in victims:
            p.kill()
        self.trace.trace("KillMachine", Machine=machine, Procs=len(victims))
        return victims

    def kill_dc(self, dc: str) -> list[SimProcess]:
        """Data-center loss: every process with the dc label dies."""
        victims = [p for p in self.processes.values() if p.dc == dc and p.alive]
        for p in victims:
            p.kill()
        self.trace.trace("KillDataCenter", DC=dc, Procs=len(victims))
        return victims

    # -- faults ------------------------------------------------------------
    def clog_pair(self, a: NetworkAddress, b: NetworkAddress, seconds: float) -> None:
        until = self.loop.now() + seconds
        self._clogged_until[(a, b)] = max(self._clogged_until.get((a, b), 0), until)
        self._clogged_until[(b, a)] = max(self._clogged_until.get((b, a), 0), until)
        self.trace.trace("ClogPair", A=str(a), B=str(b), Until=until)

    def partition(self, a: NetworkAddress, b: NetworkAddress) -> None:
        self._partitioned.add(frozenset((a, b)))
        self.trace.trace("Partition", A=str(a), B=str(b))

    def heal_partition(self, a: NetworkAddress, b: NetworkAddress) -> None:
        self._partitioned.discard(frozenset((a, b)))
        self.trace.trace("HealPartition", A=str(a), B=str(b))

    def heal_all(self) -> None:
        self._partitioned.clear()
        self._clogged_until.clear()

    # -- transport ---------------------------------------------------------
    def send(self, src: NetworkAddress, endpoint: Endpoint, payload: Any) -> None:
        """Fire-and-forget delivery with simulated latency; payload crosses
        the serialization boundary: wire-codec round trip when every nested
        piece has a registered codec (strict mode — the production
        encoders, exercised under every seed), deepcopy otherwise."""
        self.messages_sent += 1
        dst = endpoint.address
        if frozenset((src, dst)) in self._partitioned:
            self.messages_dropped += 1
            return
        latency = self.min_latency + self.rng.random() * (self.max_latency - self.min_latency)
        when = self.loop.now() + latency
        clog = self._clogged_until.get((src, dst), 0.0)
        if clog > when:
            when = clog + latency
        # FIFO per (src, dst): never deliver before the previous message
        prev = self._pair_clock.get((src, dst), 0.0)
        when = max(when, prev)
        self._pair_clock[(src, dst)] = when
        try:
            msg = decode_payload(
                encode_payload(payload, stats=self.wire, strict=True),
                stats=self.wire,
            )
        except Unencodable:
            # census by the INNER type for RPC envelopes: "RpcMessage" in
            # the fallback census would hide which payload actually lacks
            # a codec (the envelope itself always has one)
            self.wire.note_fallback(getattr(payload, "payload", payload))
            msg = copy.deepcopy(payload)

        def deliver() -> None:
            proc = self.processes.get(dst)
            if proc is None or not proc.alive:
                self.messages_dropped += 1
                self._break_reply(dst, msg)
                return
            if endpoint.token not in proc._endpoints:
                # closed/never-registered stream: fail the caller fast (the
                # TCP connection-reset analog) instead of leaving it to burn
                # its full timeout — the reference's clients see
                # broken_promise the moment the connection drops
                self.messages_dropped += 1
                self._break_reply(dst, msg)
                return
            proc._deliver(endpoint.token, msg)

        self.loop._at(when, TaskPriority.DEFAULT_ENDPOINT, deliver)

    def _break_reply(self, dead_dst: NetworkAddress, msg: Any) -> None:
        """If `msg` was an RPC expecting a reply, route BrokenPromise back to
        the caller (unless the caller itself is unreachable)."""
        reply_to = getattr(msg, "reply_to", None)
        if reply_to is None:
            return
        from .stream import RpcError  # local: stream.py imports this module

        self.send(dead_dst, reply_to, RpcError(BrokenPromise("endpoint gone")))
