"""Real TCP transport — the production twin of the simulated fabric
(fdbrpc/FlowTransport.actor.cpp:48-581 + flow/Net2.actor.cpp's reactor).

The INetwork seam contract (rpc/network.py): roles and the typed RPC layer
(rpc/stream.py) see only `net.send(src, endpoint, payload)` and a process
endpoint table.  This module implements that contract over non-blocking
sockets, so the SAME RequestStream/ReplyPromise code runs across OS
processes:

  * one `RealNetwork` per OS process, listening on one (ip, port) — its
    `RealProcess` is the local endpoint table (the FlowTransport singleton)
  * persistent length-prefixed connections per peer, dialed on first send
    and reused both ways (the reference keeps one Peer per address)
  * frames carry (dst_token, peer_addr, payload) in the runtime/serialize.py
    wire-codec format (docs/WIRE.md): binary framing with hand-written
    codecs for the hot commit-plane messages and a counted, length-guarded
    pickle fallback for cold control traffic — the same explicit-codec
    discipline the reference's versioned BinaryWriter wire has
  * writes COALESCE per connection (flow/Net2's packet coalescing): frames
    queue and flush once per reactor tick — or immediately past
    WIRE_FLUSH_BYTES — so a commit batch's resolver/TLog fan-out costs one
    syscall per peer, not one per message (WireStats counts frames/flush)
  * a dead/unreachable peer fails requests fast with BrokenPromise, exactly
    like the simulated fabric's connection-reset analog, so client retry
    behavior is identical in both worlds
  * `NetDriver` pumps the selector inside the event loop's idle gaps —
    the Net2 "reactor + run loop" shape

Demo/tests: tests/test_transport.py runs request/reply and a mini KV
service across real OS processes.
"""

from __future__ import annotations

import selectors
import socket
import ssl
import struct
import time as _time
from typing import Any, Callable

from ..runtime.core import BrokenPromise, EventLoop, Future, TaskPriority, TimedOut
from ..runtime.knobs import CoreKnobs
from ..runtime.metrics import WireStats
from ..runtime.serialize import (
    announced_protocol_version,
    decode_frame,
    decode_payload,
    encode_frame,
    encode_payload,
)
from .network import Endpoint, EndpointTable, NetworkAddress
from .stream import RpcError

_LEN = struct.Struct("<I")
MAX_FRAME = 64 << 20
# every frame is a codec frame (runtime/serialize.py encode_frame): u32
# token length + token + addr flag [+ addr] + u16 payload tag.  The
# degenerate frame (empty token, no addr, scalar payload) is 7 bytes; a
# declared length below this floor is a corrupt/hostile header, rejected
# before any body reaches the decoder.
MIN_FRAME = 7


class FrameError(ConnectionError):
    """A length-corrupt or oversized frame header: the connection is severed
    BEFORE the body reaches the deserializer — the first containment step
    on the VERDICT 'wire uses pickle' weakness (a hostile peer must not get
    to choose how many bytes we buffer, nor feed the decoder at all)."""

    def __init__(self, reason: str, declared_len: int) -> None:
        super().__init__(f"{reason} (declared {declared_len} bytes)")
        self.reason = reason
        self.declared_len = declared_len


class TLSConfig:
    """Mutual TLS for the transport — the FDBLibTLS slot.  Every node
    presents a certificate signed by the cluster CA and REQUIRES the same
    of its peer (the reference's default verify-peers policy): a plaintext
    or wrong-CA peer never completes a handshake, so the pickled-frames
    trust boundary extends only to holders of a cluster cert."""

    def __init__(self, certfile: str, keyfile: str, cafile: str) -> None:
        self.certfile = certfile
        self.keyfile = keyfile
        self.cafile = cafile

    def _ctx(self, purpose) -> ssl.SSLContext:
        ctx = ssl.SSLContext(purpose)
        ctx.load_cert_chain(self.certfile, self.keyfile)
        ctx.load_verify_locations(self.cafile)
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.check_hostname = False  # identity = cluster CA, not hostnames
        return ctx

    def server_ctx(self) -> ssl.SSLContext:
        return self._ctx(ssl.PROTOCOL_TLS_SERVER)

    def client_ctx(self) -> ssl.SSLContext:
        return self._ctx(ssl.PROTOCOL_TLS_CLIENT)


class _Conn:
    """One peer connection: framed, buffered, non-blocking."""

    def __init__(self, sock: socket.socket, addr: NetworkAddress | None) -> None:
        self.sock = sock
        self.addr = addr  # peer's LISTENING address (None until hello)
        self.out = bytearray()
        self.inbuf = bytearray()
        self.frames_queued = 0  # since the last flush (coalescing stats)
        self.connecting = False
        self.handshaking = False  # TLS handshake in progress
        self.dead = False
        # reply tokens of requests sent over this connection and not yet
        # answered: failed with BrokenPromise if the connection dies (the
        # reference fails a Peer's outstanding replies on disconnect)
        self.pending: set[str] = set()

    def queue_frame(self, blob: bytes) -> None:
        self.out += _LEN.pack(len(blob)) + blob
        self.frames_queued += 1

    def frames(self):
        """Yield complete frames out of inbuf.  Header validation happens
        as soon as the 4 length bytes arrive — an oversized or corrupt
        declared length raises FrameError immediately, before any body
        bytes are awaited (so a hostile header cannot make us buffer up to
        4 GiB) and before anything reaches the deserializer."""
        pos = 0
        n = len(self.inbuf)
        while pos + _LEN.size <= n:
            (ln,) = _LEN.unpack_from(self.inbuf, pos)
            if ln > MAX_FRAME:
                raise FrameError("oversized frame", ln)
            if ln < MIN_FRAME:
                raise FrameError("length-corrupt frame", ln)
            if pos + _LEN.size + ln > n:
                break
            yield bytes(self.inbuf[pos + _LEN.size : pos + _LEN.size + ln])
            pos += _LEN.size + ln
        del self.inbuf[:pos]


class RealProcess(EndpointTable):
    """Endpoint table + lifecycle, shape-compatible with SimProcess."""

    def __init__(self, net: "RealNetwork", address: NetworkAddress, name: str) -> None:
        super().__init__(address, name)
        self.net = net
        self._token_seq = 0
        # SimProcess shape: death hooks (SimFilesystem.open registers one).
        # A real process's death IS the OS tearing everything down, so
        # nothing ever fires these — but holders (disk-backed coordinator
        # registers) must be able to register them.
        self.on_death: list = []

    def new_token(self) -> str:
        self._token_seq += 1
        return f"{self.name}-{self._token_seq}"


class RealNetwork:
    """TCP INetwork: one per OS process.  Surface-compatible with the slice
    of SimNetwork that rpc/stream.py and the roles actually use.

    TRUST BOUNDARY: hot commit-plane frames decode through hand-written,
    length-validated binary codecs, but cold control payloads may still
    ride the counted pickle fallback (TAG_PICKLE) — and unpickling gives a
    peer code execution, so this transport remains for loopback or a
    trusted, isolated cluster network ONLY (the reference's cleartext
    FlowTransport makes the same assumption; its TLS layer is the
    production answer, docs/WIRE.md has the full trust story).  The
    default bind is 127.0.0.1; binding wider is an explicit opt-in."""

    def __init__(self, loop: EventLoop, name: str = "proc",
                 ip: str = "127.0.0.1", port: int = 0,
                 tls: TLSConfig | None = None, trace=None,
                 knobs: CoreKnobs | None = None) -> None:
        self.loop = loop
        self.tls = tls
        knobs = knobs or CoreKnobs()
        self.wire = WireStats()
        self._coalesce = bool(knobs.WIRE_COALESCE)
        self._flush_bytes = int(knobs.WIRE_FLUSH_BYTES)
        self._dirty: set[_Conn] = set()
        self.trace = trace  # optional TraceCollector for wire-error events
        self._server_ctx = tls.server_ctx() if tls else None
        self._client_ctx = tls.client_ctx() if tls else None
        self._sel = selectors.DefaultSelector()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((ip, port))
        self._listener.listen(64)
        self._listener.setblocking(False)
        self.address = NetworkAddress(ip, self._listener.getsockname()[1])
        self.process = RealProcess(self, self.address, name)
        self._conns: dict[NetworkAddress, _Conn] = {}
        self._sel.register(self._listener, selectors.EVENT_READ, ("accept", None))
        self.messages_sent = 0
        self.messages_dropped = 0
        self.frames_rejected = 0   # length-corrupt/oversized headers severed
        self.decode_failures = 0   # well-framed but undeserializable payloads
        # the version stamped into this process's hello frames (normally
        # the build's PROTOCOL_VERSION; FDBTPU_PROTOCOL_VERSION overrides
        # it for mixed-version upgrade tests)
        self.protocol_version = announced_protocol_version()
        # (peer, their version) pairs already traced as mismatched: a
        # redialing old/new pair severs on EVERY connection attempt (a
        # rolling bounce retries for seconds), but the operator-facing
        # trace gets exactly ONE TransportProtocolMismatch per pair — a
        # later MATCHING hello from the peer (it upgraded) clears its
        # entries so a genuine re-downgrade traces anew
        self._mismatch_traced: set[tuple[str, str]] = set()

    def _trace_wire_error(self, event_type: str, conn: "_Conn", **fields) -> None:
        if self.trace is not None:
            from ..runtime.trace import SEV_WARN

            self.trace.trace(
                event_type, severity=SEV_WARN,
                track_latest=event_type,
                Peer=str(conn.addr) if conn.addr else "unidentified",
                **fields,
            )

    # -- SimNetwork-compatible sending --------------------------------------
    def create_process(self, name: str) -> RealProcess:
        """The real world has ONE process per network (the OS process); the
        seam's create_process simply hands that out so role constructors and
        client factories work unchanged."""
        return self.process

    def send(self, src: NetworkAddress, endpoint: Endpoint, payload: Any) -> None:
        self.messages_sent += 1
        if endpoint.address == self.address:
            # loopback: round-trip through the wire CODEC (not pickle) so
            # co-located roles get the same serialization-boundary
            # isolation as remote peers (SimNetwork deep-copies at send
            # for exactly this reason) AND the same encoders run in every
            # deployment shape — snapshot-at-send copy semantics preserved
            msg = decode_payload(encode_payload(payload, stats=self.wire),
                                 stats=self.wire)
            self.loop._at(
                self.loop.now(), TaskPriority.DEFAULT_ENDPOINT,
                lambda: self.process._deliver(endpoint.token, msg),
            )
            return
        try:
            conn = self._conn_to(endpoint.address)
        except OSError:
            self.messages_dropped += 1
            self._break_reply(payload)
            return
        reply_to = getattr(payload, "reply_to", None)
        if reply_to is not None and reply_to.address == self.address:
            conn.pending.add(reply_to.token)
        conn.queue_frame(
            encode_frame(endpoint.token, self.address, payload, stats=self.wire)
        )
        # coalesce: queue now, flush once per reactor tick — unless the
        # queue passed the byte threshold (bound memory + burst latency)
        if not self._coalesce or len(conn.out) >= self._flush_bytes:
            self._try_flush(conn)
        else:
            self._dirty.add(conn)

    def _break_reply(self, msg: Any) -> None:
        """Connection refused/reset before delivery: fail the caller fast
        (the same broken_promise contract as the simulated fabric)."""
        reply_to = getattr(msg, "reply_to", None)
        if reply_to is None:
            return
        self.loop._at(
            self.loop.now(), TaskPriority.DEFAULT_ENDPOINT,
            lambda: self.process._deliver(
                reply_to.token, RpcError(BrokenPromise("connection failed"))
            )
            if reply_to.address == self.address
            else None,
        )

    def _conn_to(self, addr: NetworkAddress) -> _Conn:
        conn = self._conns.get(addr)
        if conn is not None and not conn.dead:
            return conn
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        conn = _Conn(s, addr)
        conn.connecting = True
        try:
            s.connect((addr.ip, addr.port))
        except BlockingIOError:
            pass
        except OSError:
            s.close()
            raise
        self._conns[addr] = conn
        self._sel.register(
            s, selectors.EVENT_READ | selectors.EVENT_WRITE, ("conn", conn)
        )
        # identify our listening address so the peer can reuse this
        # connection for traffic back to us, and stamp our protocol version
        # so a mixed-version pair severs with a NAMED reason instead of a
        # bare decode-failure loop (FlowTransport's ConnectPacket carries
        # currentProtocolVersion for the same diagnosis)
        conn.queue_frame(
            encode_frame("__hello__", self.address, self.protocol_version,
                         stats=self.wire)
        )
        return conn

    def flush_queued(self) -> None:
        """Drain the coalesced per-connection queues (one write attempt per
        dirty connection).  Called at the top of pump(), and by WallDriver
        for EVERY reactor before any of them blocks in select — a reply
        queued on net B must hit the wire before net A sleeps on its poll,
        or coalescing would add a full idle-gap to cross-net round trips."""
        if self._dirty:
            dirty, self._dirty = self._dirty, set()
            for conn in dirty:
                if not conn.dead:
                    self._try_flush(conn)

    # -- reactor -------------------------------------------------------------
    def pump(self, timeout: float) -> None:
        """Process socket readiness for up to `timeout` seconds (one poll).
        Coalesced frames queued since the last tick flush FIRST — before
        the select wait — so one reactor turn never delays its own sends."""
        self.flush_queued()
        for key, events in self._sel.select(timeout):
            kind, conn = key.data
            if kind == "accept":
                try:
                    s, _peer = self._listener.accept()
                except OSError:
                    continue
                s.setblocking(False)
                c = _Conn(s, None)
                if self._server_ctx is not None:
                    try:
                        c.sock = self._server_ctx.wrap_socket(
                            s, server_side=True, do_handshake_on_connect=False
                        )
                    except (ssl.SSLError, OSError):
                        s.close()
                        continue
                    c.handshaking = True
                self._sel.register(
                    c.sock, selectors.EVENT_READ | selectors.EVENT_WRITE,
                    ("conn", c),
                )
                continue
            if conn.connecting and (events & selectors.EVENT_WRITE):
                conn.connecting = False
                if self._client_ctx is not None:
                    # TCP is up: start the TLS handshake (the selector must
                    # track the NEW SSLSocket object wrapping the same fd)
                    try:
                        self._sel.unregister(conn.sock)
                        conn.sock = self._client_ctx.wrap_socket(
                            conn.sock, do_handshake_on_connect=False
                        )
                        self._sel.register(
                            conn.sock,
                            selectors.EVENT_READ | selectors.EVENT_WRITE,
                            ("conn", conn),
                        )
                        conn.handshaking = True
                    except (ssl.SSLError, OSError):
                        self._drop_conn(conn)
                        continue
            if conn.handshaking:
                self._pump_handshake(conn)
                continue
            if events & selectors.EVENT_WRITE:
                self._try_flush(conn)
                if not conn.out and not conn.dead:
                    self._sel.modify(conn.sock, selectors.EVENT_READ, ("conn", conn))
            if events & selectors.EVENT_READ:
                self._read(conn)

    def _pump_handshake(self, conn: _Conn) -> None:
        try:
            conn.sock.do_handshake()
        except ssl.SSLWantReadError:
            self._sel.modify(conn.sock, selectors.EVENT_READ, ("conn", conn))
            return
        except ssl.SSLWantWriteError:
            self._sel.modify(conn.sock, selectors.EVENT_WRITE, ("conn", conn))
            return
        except (ssl.SSLError, OSError):
            # wrong CA / plaintext peer / reset: sever (verify-peers policy)
            self._drop_conn(conn)
            return
        conn.handshaking = False
        self._sel.modify(
            conn.sock, selectors.EVENT_READ | selectors.EVENT_WRITE,
            ("conn", conn),
        )
        self._try_flush(conn)

    def _try_flush(self, conn: _Conn) -> None:
        if conn.connecting or conn.handshaking or conn.dead:
            return
        if conn.frames_queued and conn.out:
            # one flush event drains every frame queued since the last one
            # (frames_per_flush is the coalescing factor operators read)
            self.wire.flushes += 1
            self.wire.frames_flushed += conn.frames_queued
            conn.frames_queued = 0
        try:
            while conn.out:
                n = conn.sock.send(conn.out)
                del conn.out[:n]
        except (BlockingIOError, ssl.SSLWantWriteError, ssl.SSLWantReadError):
            self._sel.modify(
                conn.sock,
                selectors.EVENT_READ | selectors.EVENT_WRITE,
                ("conn", conn),
            )
        except OSError:
            self._drop_conn(conn)

    def _read(self, conn: _Conn) -> None:
        data = bytearray()
        try:
            while True:
                chunk = conn.sock.recv(1 << 16)
                if not chunk:
                    break
                data += chunk
                # an SSLSocket may hold decrypted bytes beyond one recv
                if not (isinstance(conn.sock, ssl.SSLSocket) and conn.sock.pending()):
                    break
        except (BlockingIOError, ssl.SSLWantReadError, ssl.SSLWantWriteError):
            # SSLWantWrite on a READ is legal (renegotiation with a full
            # send buffer) — benign, like the Want* cases in _try_flush
            if not data:
                return
        except OSError:
            self._drop_conn(conn)
            return
        if not data:
            self._drop_conn(conn)
            return
        conn.inbuf += data
        try:
            frames = list(conn.frames())
        except FrameError as e:
            # connection-level rejection: the declared length is hostile or
            # corrupt, so nothing here may reach the deserializer — sever
            # with a traced error (the reference severs on malformed
            # ConnectPacket lengths the same way)
            self.frames_rejected += 1
            self._trace_wire_error(
                "TransportFrameRejected", conn,
                Reason=e.reason, DeclaredLen=e.declared_len,
            )
            self._drop_conn(conn)
            return
        try:
            decoded = [decode_frame(b, self.wire) for b in frames]
        except Exception as e:  # noqa: BLE001 — corrupt peer: sever, don't die
            # CodecError (truncated/unknown-tag codec body) and a bad
            # pickle-fallback body land here alike: well-framed but
            # undecodable is a deserializer-level failure — severed and
            # counted, same containment as the oversized-header path
            self.decode_failures += 1
            self._trace_wire_error(
                "TransportDecodeFailed", conn, Error=repr(e)[:200]
            )
            self._drop_conn(conn)
            return
        for token, peer_addr, payload in decoded:
            if token == "__hello__":
                if payload is not None and payload != self.protocol_version:
                    # mixed-version pair: sever with a NAMED reason (a
                    # pre-codec peer never even reaches here — its pickled
                    # hello fails decode_frame above).  Deduped per
                    # (peer, their version): during a rolling bounce the
                    # old/new pair redials every retry interval and severs
                    # each time, but exactly one mismatch event per pair
                    # reaches the trace plane
                    theirs = (hex(payload) if isinstance(payload, int)
                              else repr(payload)[:40])
                    key = (str(peer_addr), theirs)
                    if key not in self._mismatch_traced:
                        self._mismatch_traced.add(key)
                        self._trace_wire_error(
                            "TransportProtocolMismatch", conn,
                            Ours=hex(self.protocol_version), Theirs=theirs,
                            PeerAddress=str(peer_addr),
                        )
                    self._drop_conn(conn)
                    return
                # a matching hello proves the peer runs OUR version now:
                # forget any mismatch we traced against its old one
                self._mismatch_traced = {
                    k for k in self._mismatch_traced
                    if k[0] != str(peer_addr)
                }
                conn.addr = peer_addr
                # reuse this connection for outbound traffic to the peer
                if peer_addr not in self._conns or self._conns[peer_addr].dead:
                    self._conns[peer_addr] = conn
                continue
            conn.pending.discard(token)
            self.loop._at(
                self.loop.now(), TaskPriority.DEFAULT_ENDPOINT,
                lambda t=token, p=payload: self._deliver_or_bounce(t, p),
            )

    def _deliver_or_bounce(self, token: str, payload: Any) -> None:
        """Deliver; a request for a closed/unknown stream bounces
        BrokenPromise to the caller — the same fast-fail the simulated
        fabric gives, so retry behavior matches across the seam."""
        if token in self.process._endpoints:
            self.process._deliver(token, payload)
            return
        reply_to = getattr(payload, "reply_to", None)
        if reply_to is not None:
            self.send(
                self.address, reply_to, RpcError(BrokenPromise("endpoint gone"))
            )

    def _drop_conn(self, conn: _Conn) -> None:
        conn.dead = True
        self._dirty.discard(conn)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn.addr is not None and self._conns.get(conn.addr) is conn:
            del self._conns[conn.addr]
        # fail every request still waiting on this peer — fast, like the
        # simulated fabric's connection-reset analog
        pending, conn.pending = conn.pending, set()
        for token in pending:
            self.loop._at(
                self.loop.now(), TaskPriority.DEFAULT_ENDPOINT,
                lambda t=token: self.process._deliver(
                    t, RpcError(BrokenPromise("connection reset"))
                ),
            )

    def close(self) -> None:
        # sever every registered socket (including accepted-but-unhelloed
        # peers that never made it into _conns), then the selector itself
        for key in list(self._sel.get_map().values()):
            kind, conn = key.data
            if kind == "conn":
                self._drop_conn(conn)
        try:
            self._sel.unregister(self._listener)
        except (KeyError, ValueError):
            pass
        self._listener.close()
        self._sel.close()


class WallDriver:
    """Drives an EventLoop against the wall clock WITH reactor IO — the
    Net2 "reactor + run loop" shape.  `pumps` is one or more
    `pump(timeout)` callables (RealNetwork.pump, ClientGateway.pump, ...);
    each idle gap until the next timer is split across them.  THE single
    wall-clock driver — tools/gateway.py's GatewayDriver is a thin alias."""

    def __init__(self, loop: EventLoop, pumps: list[Callable[[float], None]]) -> None:
        self.loop = loop
        self.pumps = list(pumps)
        # reactors with coalesced write queues (bound `net.pump` methods):
        # their queues must ALL drain before any pump blocks in select
        self._flushers = [
            flush
            for p in self.pumps
            if (flush := getattr(getattr(p, "__self__", None), "flush_queued", None))
        ]
        self._origin = _time.monotonic() - loop.now()  # flowlint: ok wall-clock (the wall driver anchors virtual time to the wall)

    def _tick(self) -> None:
        """One reactor turn: drain every due timer, spend the gap until the
        next one polling the reactors, and anchor virtual time to the wall
        (run_one never moves time backwards, so the anchor is always safe —
        the single place this time model lives for the real-IO driver)."""
        now = _time.monotonic()  # flowlint: ok wall-clock (wall driver tick)
        while self.loop._heap and self._origin + self.loop._heap[0][0] <= now:
            self.loop.run_one()
            now = _time.monotonic()  # flowlint: ok wall-clock (wall driver tick)
        # cross-reactor flush barrier: frames the timer turn just queued on
        # ANY net go out before the FIRST net sleeps on its poll
        for flush in self._flushers:
            flush()
        gap = 0.02
        if self.loop._heap:
            gap = min(max((self._origin + self.loop._heap[0][0]) - now, 0.0), 0.02)
        share = gap / max(len(self.pumps), 1)
        for pump in self.pumps:
            pump(share)
        self.loop._now = max(self.loop._now, _time.monotonic() - self._origin)  # flowlint: ok wall-clock (the anchor itself)

    def run_until(self, fut: Future, wall_timeout: float | None = None) -> Any:
        start = _time.monotonic()  # flowlint: ok wall-clock (wall_timeout is a host bound by contract)
        while not fut.done():
            if wall_timeout is not None and _time.monotonic() - start > wall_timeout:  # flowlint: ok wall-clock (wall_timeout is a host bound by contract)
                raise TimedOut(f"wall timeout {wall_timeout}s")
            self._tick()
        return fut.result()

    def serve_forever(self, wall_timeout: float | None = None) -> None:
        """Pump IO + timers until the deadline (server main loop)."""
        start = _time.monotonic()  # flowlint: ok wall-clock (server main-loop deadline is host wall)
        while wall_timeout is None or _time.monotonic() - start < wall_timeout:  # flowlint: ok wall-clock (server main-loop deadline is host wall)
            self._tick()


class NetDriver(WallDriver):
    """WallDriver over one RealNetwork (the common single-reactor case)."""

    def __init__(self, loop: EventLoop, net: RealNetwork) -> None:
        super().__init__(loop, [net.pump])
        self.net = net
