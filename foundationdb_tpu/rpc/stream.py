"""Typed RPC: RequestStream / ReplyPromise over the network fabric.

The reference's RPC is promises that travel the wire (fdbrpc/fdbrpc.h:217):
a request carries an embedded ReplyPromise token; whoever holds the request
can fire the reply back to the caller's endpoint.  Same shape here:

  server:  rs = RequestStream(process, "wlt:commit")
           req = await rs.next()          # ReceivedRequest
           req.reply(result)              # or req.reply_error(exc)

  client:  ref = RequestStreamRef(net, my_process, rs.endpoint)
           result = await ref.get_reply(payload)

Reply routing is token-addressed back to the caller (networksender analog).
A killed/rebooted server silently drops state; callers protect themselves
with `get_reply(payload, timeout=...)` plus the failure monitor — identical
division of labor to the reference (fdbrpc/FailureMonitor.h).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ..runtime.combinators import timeout_error
from ..runtime.core import Future, FutureStream, Promise, TimedOut
from .network import Endpoint, NetworkAddress, SimNetwork, SimProcess


@dataclasses.dataclass
class RpcMessage:
    """Wire envelope: payload + optional reply endpoint + optional sampled
    trace context.  `spans` carries the debug IDs of sampled transactions
    riding this message (the g_traceBatch wire propagation: the receiving
    process's role code lands its stations in ITS TraceBatch under the
    originating IDs, so tools/trace_tool.py can join one transaction's
    journey across OS processes).  None on the un-sampled hot path — the
    codec keeps the spanless layout byte-identical (zero wire cost)."""

    payload: Any
    reply_to: Endpoint | None = None
    spans: tuple | None = None  # tuple[str, ...] of sampled debug IDs


@dataclasses.dataclass
class RpcError:
    """Wire form of an exception reply."""

    error: Exception


class ReplyPromise:
    """Client-side reply slot with its own endpoint token (the promise that
    'travels' — its token does, and replies route back to it)."""

    def __init__(self, process: SimProcess) -> None:
        self._process = process
        self._promise = Promise()
        self._token = "rp:" + process.new_token()
        process.register(self._token, self._on_reply)

    @property
    def endpoint(self) -> Endpoint:
        return Endpoint(self._process.address, self._token)

    @property
    def future(self) -> Future:
        return self._promise.future

    def _on_reply(self, payload: Any) -> None:
        self._process.unregister(self._token)
        if self._promise.future.done():
            return
        if isinstance(payload, RpcError):
            self._promise.fail(payload.error)
        else:
            self._promise.send(payload)

    def dispose(self) -> None:
        """Unregister without a reply (abandoned RPC)."""
        self._process.unregister(self._token)


class ReceivedRequest:
    """Server-side view of one request: payload + reply capability +
    whatever sampled trace spans rode the envelope (role code lands its
    g_trace_batch stations under them)."""

    __slots__ = ("payload", "_reply_to", "_process", "replied", "spans")

    def __init__(self, payload: Any, reply_to: Endpoint | None, process: SimProcess,
                 spans: tuple | None = None) -> None:
        self.payload = payload
        self._reply_to = reply_to
        self._process = process
        self.replied = False
        self.spans = spans

    def reply(self, value: Any = None) -> None:
        self.replied = True
        if self._reply_to is not None and self._process.alive:
            self._process.net.send(self._process.address, self._reply_to, value)

    def reply_error(self, err: Exception) -> None:
        self.replied = True
        if self._reply_to is not None and self._process.alive:
            self._process.net.send(self._process.address, self._reply_to, RpcError(err))


class RequestStream:
    """Server-side stream of typed requests at a (usually well-known) token."""

    def __init__(self, process: SimProcess, token: str | None = None,
                 unique: bool = False) -> None:
        self._process = process
        if token is None:
            self._token = "rs:" + process.new_token()
        elif unique:
            # per-INSTANCE endpoint: successive generations' roles may share
            # a worker process, and a well-known token would make a deposed
            # role's callers silently reach its successor (role interfaces
            # in the reference carry UID-based tokens for exactly this)
            self._token = f"{token}:{process.new_token()}"
        else:
            self._token = token
        self.requests = FutureStream()
        process.register(self._token, self._on_message)

    @property
    def endpoint(self) -> Endpoint:
        return Endpoint(self._process.address, self._token)

    def _on_message(self, msg: RpcMessage) -> None:
        self.requests.send(
            ReceivedRequest(
                msg.payload, msg.reply_to, self._process,
                getattr(msg, "spans", None),
            )
        )

    def next(self) -> Future:
        """Future of the next ReceivedRequest."""
        return self.requests.pop()

    def close(self) -> None:
        self._process.unregister(self._token)
        self.requests.close()


def _register_rpc_codec() -> None:
    """RpcMessage's wire codec (runtime/serialize.py registry): reply
    endpoint + nested payload through `encode_any`, so a registered hot
    payload stays binary end to end and an exotic one degrades to a
    counted pickle body — never a whole-frame pickle.

    Two layouts, one type: tag 60 is the spanless envelope (byte-identical
    to the pre-tracing wire — an un-sampled message costs ZERO extra
    bytes), tag 61 prefixes the same body with the sampled debug-ID spans
    (`u16 n + n × (u16 len + utf8)`)."""
    import struct as _struct

    from ..runtime import serialize as _wire

    _ST_I = _struct.Struct("<I")
    _ST_H = _struct.Struct("<H")

    def _enc_envelope(o: RpcMessage, stats, strict) -> bytes:
        rt = o.reply_to
        if rt is not None and rt.address is None:
            # the decoder keys the token read off the address flag, so an
            # address-less endpoint can't ride the codec — raising here
            # downgrades to the counted fallback (parity preserved) rather
            # than silently mis-framing
            raise _wire.CodecError("reply endpoint without address")
        tag, body = _wire.encode_any(o.payload, stats, strict)
        parts: list = []
        _wire.write_addr(parts, rt.address if rt is not None else None)
        if rt is not None:
            tok = rt.token.encode("utf-8")
            parts.append(_ST_I.pack(len(tok)))
            parts.append(tok)
        parts.append(_ST_H.pack(tag))
        parts.append(body)
        return b"".join(parts)

    def enc(o: RpcMessage, stats, strict):
        body = _enc_envelope(o, stats, strict)
        sp = o.spans
        if not sp:
            return body  # tag 60: the spanless wire, unchanged
        parts = [_ST_H.pack(len(sp))]
        for s in sp:
            sb = s.encode("utf-8")
            parts.append(_ST_H.pack(len(sb)))
            parts.append(sb)
        parts.append(body)
        return 61, b"".join(parts)

    def _dec_envelope(buf: bytes, pos: int, stats, spans) -> RpcMessage:
        addr, pos = _wire.read_addr(buf, pos)
        reply_to = None
        if addr is not None:
            (ntok,) = _ST_I.unpack_from(buf, pos)
            pos += 4
            token = buf[pos : pos + ntok].decode("utf-8")
            pos += ntok
            reply_to = Endpoint(addr, token)
        (tag,) = _ST_H.unpack_from(buf, pos)
        return RpcMessage(
            _wire.decode_any(tag, buf[pos + 2 :], stats), reply_to, spans
        )

    def dec(buf: bytes, stats) -> RpcMessage:
        return _dec_envelope(buf, 0, stats, None)

    def dec_spanned(buf: bytes, stats) -> RpcMessage:
        (n,) = _ST_H.unpack_from(buf, 0)
        pos = 2
        spans = []
        for _ in range(n):
            (ln,) = _ST_H.unpack_from(buf, pos)
            pos += 2
            sb = buf[pos : pos + ln]
            if len(sb) != ln:
                raise _wire.CodecError("truncated span id")
            spans.append(sb.decode("utf-8"))
            pos += ln
        return _dec_envelope(buf, pos, stats, tuple(spans))

    _wire.register_codec(60, RpcMessage, enc, dec)
    _wire.register_decoder(61, dec_spanned)


_register_rpc_codec()


class RequestStreamRef:
    """Client-side handle to a remote RequestStream."""

    def __init__(self, net: SimNetwork, process: SimProcess, endpoint: Endpoint) -> None:
        self._net = net
        self._process = process
        self.endpoint = endpoint

    def send(self, payload: Any, spans: tuple | None = None) -> None:
        """One-way, at-most-once (FlowTransport unreliable send)."""
        self._net.send(
            self._process.address, self.endpoint, RpcMessage(payload, None, spans)
        )

    def get_reply(self, payload: Any, timeout: float | None = None,
                  spans: tuple | None = None) -> Future:
        rp = ReplyPromise(self._process)
        self._net.send(
            self._process.address, self.endpoint,
            RpcMessage(payload, rp.endpoint, spans),
        )
        if timeout is None:
            return rp.future
        out = timeout_error(self._net.loop, rp.future, timeout)
        # on timeout the reply will never be consumed: drop the endpoint so
        # abandoned RPCs don't leak entries in the process endpoint table
        out.add_done_callback(lambda _f: rp.dispose())
        return out
