"""Cluster-wide failure monitor — the shared liveness map
(fdbrpc/FailureMonitor.h:65 FailureStatus, :123 SimpleFailureMonitor;
fdbclient/FailureMonitorClient.actor.cpp:34 clients polling the cluster
controller's aggregated view).

One FailureMonitor per cluster, FED by the processes that already observe
liveness — the controller's pipeline heartbeats and data distribution's
storage pings — and CONSULTED by everyone else: client load-balancing
skips replicas marked failed instead of paying a per-request timeout to
rediscover what the cluster already knows (the reference's loadBalance
checks IFailureMonitor::getState before picking alternatives).

The sim can LIE to it (`set_override`) — the partition-test hook: mark a
live address failed (or a dead one healthy) and observe how consumers
behave on bad information, exactly what the reference's simulator does to
FailureMonitor state."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class FailureStatus:
    failed: bool
    since: float  # when this status was established


class FailureMonitor:
    def __init__(self, clock) -> None:
        self._clock = clock
        self._status: dict = {}    # address -> FailureStatus
        self._override: dict = {}  # address -> bool (sim lies)
        self.transitions = 0
        # accelerator-backend health, fed by each resolver's
        # DeviceSupervisor (conflict/supervisor.py): name -> health dict
        # (state/trips/time degraded...).  Kept apart from the process map —
        # a degraded DEVICE is a performance event, not a dead process, and
        # consumers must not reroute around a resolver whose CPU fallback
        # is serving correctly.
        self._devices: dict = {}
        self.device_transitions = 0

    def set_status(self, address, failed: bool) -> None:
        """Feed an observation (heartbeat result).  Idempotent: `since`
        moves only on transitions."""
        cur = self._status.get(address)
        if cur is None or cur.failed != failed:
            self._status[address] = FailureStatus(failed, self._clock())
            self.transitions += 1

    def is_failed(self, address) -> bool:
        if address in self._override:
            return self._override[address]
        st = self._status.get(address)
        return st is not None and st.failed

    def status(self, address) -> FailureStatus | None:
        return self._status.get(address)

    def failed_addresses(self) -> list:
        return [
            a for a in self._status.keys() | self._override.keys()
            if self.is_failed(a)
        ]

    # -- device-backend health (conflict/supervisor.py feed) -----------------
    def note_device(self, name: str, health: dict) -> None:
        """Record a device supervisor's health snapshot; `since` semantics
        match set_status — transitions counted on state changes only."""
        prev = self._devices.get(name)
        entry = dict(health)
        if prev is None or prev.get("state") != entry.get("state"):
            entry["since"] = self._clock()
            self.device_transitions += 1
        else:
            entry["since"] = prev.get("since")
        self._devices[name] = entry

    def device_report(self) -> dict:
        """name -> latest health snapshot (status.py rolls this up)."""
        return {k: dict(v) for k, v in self._devices.items()}

    def degraded_devices(self) -> list[str]:
        return sorted(
            k for k, v in self._devices.items() if v.get("state") == "degraded"
        )

    # -- simulation hook -----------------------------------------------------
    def set_override(self, address, failed: bool | None) -> None:
        """Lie to consumers (partition tests): `failed=None` clears."""
        if failed is None:
            self._override.pop(address, None)
        else:
            self._override[address] = failed

    def forget(self, address) -> None:
        """An address left the cluster (process retired): drop its entry so
        the map doesn't grow with every recovery's fresh processes."""
        self._status.pop(address, None)
        self._override.pop(address, None)
