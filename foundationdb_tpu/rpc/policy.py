"""Declarative replication policies — PolicyOne / PolicyAcross evaluated
against process locality (fdbrpc/ReplicationPolicy.h:101 PolicyOne, :121
PolicyAcross; fdbrpc/Locality.h LocalityData).

The reference validates every team and coordinator selection against a
policy object built from the redundancy mode ("double" = two replicas
across machines, "three_datacenter" = three across DCs, ...).  This module
is that object: `validate` judges an existing placement, `select` chooses a
satisfying subset from candidates (the team-builder path).  Policies nest —
Across(2, "dc", Across(2, "machine", One())) is "two DCs, two machines
each" — exactly the reference's composition.

Deterministic: `select` is stable in candidate order, so same seed ⇒ same
placement (the simulation's determinism contract).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class Locality:
    """One process's placement attributes (LocalityData: processId,
    machineId, dcId)."""

    process: str
    machine: str | None = None
    dc: str | None = None

    @classmethod
    def of(cls, proc) -> "Locality":
        return cls(
            proc.name,
            getattr(proc, "machine", None),
            getattr(proc, "dc", None),
        )

    def get(self, attr: str):
        return getattr(self, attr)


class ReplicationPolicy:
    """Base: how many replicas, and does a placement satisfy the policy?"""

    def replicas(self) -> int:
        raise NotImplementedError

    def validate(self, locs: Sequence[Locality]) -> bool:
        raise NotImplementedError

    def select(self, candidates: Sequence[Locality]) -> list[int] | None:
        """Indices of a satisfying subset of `candidates` (stable order),
        or None if the candidates cannot satisfy the policy."""
        raise NotImplementedError


class PolicyOne(ReplicationPolicy):
    """Any single replica (ReplicationPolicy.h:101)."""

    def replicas(self) -> int:
        return 1

    def validate(self, locs: Sequence[Locality]) -> bool:
        return len(locs) >= 1

    def select(self, candidates: Sequence[Locality]) -> list[int] | None:
        return [0] if candidates else None

    def __repr__(self) -> str:
        return "One()"


class PolicyAcross(ReplicationPolicy):
    """`count` distinct values of `attr`, each satisfying `sub`
    (ReplicationPolicy.h:121 PolicyAcross).  A None attribute value is its
    own group per process (no locality info = assume distinct, matching the
    reference's treatment of unset locality keys)."""

    def __init__(self, count: int, attr: str, sub: ReplicationPolicy | None = None) -> None:
        if count < 1:
            raise ValueError("count must be >= 1")
        if attr not in ("machine", "dc", "process"):
            raise ValueError(f"unknown locality attribute {attr!r}")
        self.count = count
        self.attr = attr
        self.sub = sub or PolicyOne()

    def replicas(self) -> int:
        return self.count * self.sub.replicas()

    def _groups(self, locs: Sequence[Locality]) -> dict:
        groups: dict = {}
        for i, loc in enumerate(locs):
            v = loc.get(self.attr)
            key = v if v is not None else ("\x00unset", loc.process)
            groups.setdefault(key, []).append(i)
        return groups

    def validate(self, locs: Sequence[Locality]) -> bool:
        ok_groups = sum(
            1
            for idxs in self._groups(locs).values()
            if self.sub.validate([locs[i] for i in idxs])
        )
        return ok_groups >= self.count

    def select(self, candidates: Sequence[Locality]) -> list[int] | None:
        chosen: list[int] = []
        groups = 0
        # stable: groups visited in first-appearance order
        seen: list = []
        gmap = self._groups(candidates)
        for loc in candidates:
            v = loc.get(self.attr)
            key = v if v is not None else ("\x00unset", loc.process)
            if key not in seen:
                seen.append(key)
        for key in seen:
            if groups >= self.count:
                break
            idxs = gmap[key]
            sub_sel = self.sub.select([candidates[i] for i in idxs])
            if sub_sel is None:
                continue
            chosen.extend(idxs[j] for j in sub_sel)
            groups += 1
        return chosen if groups >= self.count else None

    def __repr__(self) -> str:
        return f"Across({self.count}, {self.attr!r}, {self.sub!r})"


# redundancy mode -> (replication factor, policy) — the `configure
# redundancy=` vocabulary (fdbclient/DatabaseConfiguration.cpp modes)
REDUNDANCY_MODES: dict[str, ReplicationPolicy] = {
    "single": PolicyOne(),
    "double": PolicyAcross(2, "machine"),
    "triple": PolicyAcross(3, "machine"),
    "three_datacenter": PolicyAcross(3, "dc"),
}


def policy_for_redundancy(mode: str) -> ReplicationPolicy:
    if mode not in REDUNDANCY_MODES:
        raise ValueError(
            f"unknown redundancy mode {mode!r}; choose from {sorted(REDUNDANCY_MODES)}"
        )
    return REDUNDANCY_MODES[mode]
