"""Per-phase device microbenchmarks for the conflict kernel.

Times each primitive of conflict/device.py's resolve_core at bench.py's
shapes (CAP=2^19, R=16K, Wn=8K, W=5) so optimization attacks the measured
dominator, mirroring skipListTest's per-phase PerfCounters
(fdbserver/SkipList.cpp:1412-1502).

Usage:  python profile_kernel.py            # primitive microbench (device)
        JAX_PLATFORMS=cpu python profile_kernel.py
        python profile_kernel.py --phase    # whole-kernel phase breakdown
                                            # over the new KernelStats
                                            # sort/scan/merge/compact
                                            # counters (docs/KERNEL.md)

--phase drives a real DeviceConflictSet through a synthetic stream with
FDBTPU_PHASE_TIMING on (each phase its own dispatch + barrier) and prints
the per-phase wall-time split plus the incremental-merge counters — the
same numbers bench.py lands in BENCH json.  Shape knobs: PROFILE_BATCHES,
PROFILE_TXNS, PROFILE_CAP (env).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np


def drive_phase_stream(n_batches: int, n_txns: int, cap: int,
                       run_slots: int = 4, seed: int = 11):
    """Shared synthetic resolve stream with phase timing on — the single
    driver behind `profile_kernel.py --phase` AND `bench.py --cpu-phase`,
    so the two phase reports operators compare never desynchronize.
    Returns (DeviceConflictSet, kernel_stats snapshot)."""
    os.environ["FDBTPU_PHASE_TIMING"] = "1"
    from foundationdb_tpu.conflict.api import TxInfo
    from foundationdb_tpu.conflict.device import DeviceConflictSet

    rng = np.random.default_rng(seed)
    dev = DeviceConflictSet(capacity=cap, run_slots=run_slots)
    version = 0
    for _ in range(n_batches):
        version += 1
        txns = []
        for _ in range(n_txns):
            # 8-byte keys: the [k, k+\x00) end key must still encode
            # (the TxInfo path, unlike bench's device_pack, uses encode_keys)
            ks = [rng.bytes(8) for _ in range(3)]
            txns.append(
                TxInfo(
                    max(version - 2, 0),
                    [(k, k + b"\x00") for k in ks[:2]],
                    [(ks[2], ks[2] + b"\x00")],
                )
            )
        dev.resolve_batch(version, txns)
    return dev, dev.kernel_stats()


def phase_main() -> None:
    import jax

    n_batches = int(os.environ.get("PROFILE_BATCHES", "12"))
    n_txns = int(os.environ.get("PROFILE_TXNS", "512"))
    cap = int(os.environ.get("PROFILE_CAP", str(1 << 15)))
    dev, snap = drive_phase_stream(n_batches, n_txns, cap)
    print(
        f"backend: {jax.default_backend()}  incremental: {dev._incremental}"
        f"  probe: {dev._probe_impl}  cap: {cap}"
    )
    phase = snap["phase"]
    total = sum(phase.values()) or 1.0
    print(f"\n{n_batches} batches x {n_txns} txns "
          f"(runs_appended={snap['runs_appended']} "
          f"compactions={snap['compactions']} "
          f"full_merges={snap['full_merges']}):")
    for name in ("sort_ms", "scan_ms", "merge_ms", "compact_ms"):
        ms = phase[name]
        print(f"  {name:<12s} {ms:9.2f} ms  {100 * ms / total:5.1f}%")
    # host input-pipeline split (docs/KERNEL.md "Input pipeline"):
    # pack = encode (flatten + lane encode) + pad (bucket/arena fill)
    # + h2d (explicit device staging, populated by pipelined feeders)
    print(f"  {'pack_ms':<12s} {snap['pack_ms']:9.2f} ms  "
          f"(encode {snap['encode_ms']:.2f} + pad {snap['pad_ms']:.2f} + "
          f"h2d {snap['h2d_ms']:.2f})")
    print(f"  resolve p50 {snap['resolve_ms_p50']:.2f} ms  "
          f"p99 {snap['resolve_ms_p99']:.2f} ms")


_RTT_MS = [0.0]  # measured host<->device round-trip floor, subtracted


def _force(out):
    """Flatten outputs and fetch one element of each to host — the only
    reliable completion barrier over the axon tunnel (block_until_ready
    returns at dispatch-accept, not execution-done)."""
    import jax

    leaves = jax.tree_util.tree_leaves(out)
    return [np.asarray(l).ravel()[:1] for l in leaves]


def bench_one(name, fn, *args, n=5):
    import jax

    fn = jax.jit(fn)
    _force(fn(*args))  # compile + warm
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        _force(fn(*args))
        ts.append(time.perf_counter() - t0)
    ms = sorted(ts)[len(ts) // 2] * 1e3 - _RTT_MS[0]
    print(f"  {name:<42s} {ms:9.2f} ms")
    return ms


def main() -> None:
    import jax
    import jax.numpy as jnp

    from foundationdb_tpu.ops.rmq import build_sparse_table, query_sparse_table

    print(f"backend: {jax.default_backend()}  devices: {jax.devices()}")

    # round-trip floor: time a trivial fetch, subtract from every sample
    one = jnp.ones((8,), jnp.int32)
    trivial = jax.jit(lambda x: x + 1)
    _force(trivial(one))
    ts = []
    for _ in range(7):
        t0 = time.perf_counter()
        _force(trivial(one))
        ts.append(time.perf_counter() - t0)
    _RTT_MS[0] = sorted(ts)[len(ts) // 2] * 1e3
    print(f"host<->device round-trip floor: {_RTT_MS[0]:.2f} ms (subtracted)")

    CAP = 1 << 19
    W = 5
    R, Wn = 16384, 8192
    B = 8192
    M = CAP + 2 * Wn
    rng = np.random.default_rng(7)

    ks = jnp.asarray(
        np.sort(rng.integers(0, 2**32, size=(CAP,), dtype=np.uint64)).astype(np.uint32)
    )
    ks_rows = jnp.asarray(rng.integers(0, 2**32, size=(CAP, W), dtype=np.uint64).astype(np.uint32))
    vs = jnp.asarray(rng.integers(0, 1 << 20, size=(CAP,), dtype=np.int64).astype(np.int32))
    q_rows = jnp.asarray(rng.integers(0, 2**32, size=(2 * R + 2 * Wn, W), dtype=np.uint64).astype(np.uint32))
    bidx = jnp.asarray(np.arange(0, 65537, dtype=np.int32) * (CAP // 65536))

    scat_idx = jnp.asarray(np.sort(rng.choice(M, size=2 * Wn, replace=False)).astype(np.int32))
    scat_rows = jnp.asarray(rng.integers(0, 2**32, size=(2 * Wn, W), dtype=np.uint64).astype(np.uint32))
    pos_old = jnp.asarray((np.arange(CAP) + np.linspace(0, 2 * Wn, CAP).astype(np.int64)).astype(np.int32))
    gidx = jnp.asarray(rng.integers(0, CAP, size=(M,), dtype=np.int64).astype(np.int32))

    print(f"shapes: CAP={CAP} R={R} Wn={Wn} M={M} W={W}")

    # --- search primitives ---
    from foundationdb_tpu.conflict.device import _bucketed_lower_bound
    bench_one(
        "search: bucketed_lower_bound 49K q, 11 it",
        lambda k, bi, q: _bucketed_lower_bound(k, bi, jnp.int32(CAP), q, 11)[0],
        ks_rows, bidx, q_rows,
    )

    # --- phase 1 ---
    g_lo = jnp.asarray(rng.integers(0, CAP - 1, size=(R,), dtype=np.int64).astype(np.int32))
    g_hi = jnp.minimum(g_lo + jnp.asarray(rng.integers(1, 3, size=(R,), dtype=np.int64).astype(np.int32)), CAP - 1)
    bench_one("p1: build_sparse_table over CAP", lambda v: build_sparse_table(v, jnp.maximum, 0), vs)
    tbl = jax.jit(lambda v: build_sparse_table(v, jnp.maximum, 0))(vs)
    bench_one(
        "p1: query_sparse_table 16K ranges",
        lambda t, lo, hi: query_sparse_table(t, lo, hi, jnp.maximum, 0),
        tbl, g_lo, g_hi,
    )

    # --- phase 2 (one fixpoint iteration) ---
    rb_r = jnp.asarray(rng.integers(0, 2 * (R + Wn), size=(R,), dtype=np.int64).astype(np.int32))
    re_r = rb_r + 1
    wb_r = jnp.asarray(rng.integers(0, 2 * (R + Wn), size=(Wn,), dtype=np.int64).astype(np.int32))
    we_r = wb_r + 1
    w_cand = jnp.asarray(rng.integers(0, B, size=(Wn,), dtype=np.int64).astype(np.int32))

    def p2_iter(rb_r, re_r, wb_r, we_r, w_cand):
        ov = (wb_r[None, :] < re_r[:, None]) & (rb_r[:, None] < we_r[None, :])
        return jnp.min(jnp.where(ov, w_cand[None, :], 2**31 - 1), axis=1)

    bench_one("p2: one R x Wn masked-min iteration", p2_iter, rb_r, re_r, wb_r, we_r, w_cand)

    # --- phase 3 primitives ---
    bench_one(
        "p3: row scatter 16K rows into M",
        lambda idx, rows: jnp.full((M, W), 0xFFFFFFFF, jnp.uint32).at[idx].set(rows, mode="drop"),
        scat_idx, scat_rows,
    )
    bench_one(
        "p3: row scatter CAP rows into M (pos_old)",
        lambda idx, rows: jnp.full((M, W), 0xFFFFFFFF, jnp.uint32).at[idx].set(rows, mode="drop"),
        pos_old, ks_rows,
    )
    bench_one(
        "p3: BOTH merge scatters (old+new)",
        lambda po, kr, pn, ur: jnp.full((M, W), 0xFFFFFFFF, jnp.uint32)
        .at[po].set(kr, mode="drop").at[pn].set(ur, mode="drop"),
        pos_old, ks_rows, scat_idx, scat_rows,
    )
    bench_one(
        "p3: scalar scatter-add 16K into M",
        lambda idx: jnp.zeros(M, jnp.int32).at[idx].add(1, mode="drop"),
        scat_idx,
    )
    bench_one(
        "p3: scalar scatter CAP vals into M",
        lambda idx, v: jnp.zeros(M, jnp.int32).at[idx].set(v, mode="drop"),
        pos_old, vs,
    )
    bench_one("p3: cumsum over M", lambda x: jnp.cumsum(x), jnp.zeros(M, jnp.int32))
    bench_one(
        "p3: gather M rows from CAP",
        lambda k, i: jnp.take(k, i, axis=0),
        ks_rows, gidx,
    )
    keep = jnp.asarray(rng.random(M) < 0.5)
    mrows = jnp.asarray(rng.integers(0, 2**32, size=(M, W), dtype=np.uint64).astype(np.uint32))

    def compact_scatter(keep, rows):
        pos = jnp.where(keep, jnp.cumsum(keep.astype(jnp.int32)) - 1, M)
        return jnp.full((CAP, W), 0xFFFFFFFF, jnp.uint32).at[pos].set(rows, mode="drop")

    bench_one("p3: compaction scatter M rows -> CAP", compact_scatter, keep, mrows)

    # sort alternatives
    bench_one(
        "alt: argsort 16K int32",
        lambda x: jnp.argsort(x),
        jnp.asarray(rng.integers(0, M, size=(2 * Wn,), dtype=np.int64).astype(np.int32)),
    )
    bench_one(
        "alt: lexsort M rows (W keys)",
        lambda r: jnp.lexsort(tuple(r[:, w] for w in range(W - 1, -1, -1))),
        mrows,
    )
    bench_one(
        "alt: sort M int32 + payload",
        lambda k, p: jax.lax.sort((k, p), num_keys=1),
        jnp.asarray(rng.integers(0, 2**31, size=(M,), dtype=np.int64).astype(np.int32)),
        jnp.asarray(np.arange(M, dtype=np.int32)),
    )

    # --- bucket rebuild ---
    h_all = (ks_rows[:, 0] >> 16).astype(jnp.int32)
    bench_one(
        "bucket: histogram scatter-add CAP -> 65K + cumsum",
        lambda h: jnp.cumsum(jnp.zeros(65537, jnp.int32).at[h + 1].add(1)),
        h_all,
    )

    # --- whole kernel at bench shapes for reference ---
    from foundationdb_tpu.conflict.device import resolve_core
    import functools

    kern = functools.partial(
        jax.jit, static_argnames=("cap", "n_txn", "n_read", "n_write", "search_iters")
    )(resolve_core)
    rb = q_rows[:R]
    re_ = q_rows[R : 2 * R]
    wb = q_rows[2 * R : 2 * R + Wn]
    we = q_rows[2 * R + Wn :]
    r_tx = jnp.asarray(np.repeat(np.arange(B, dtype=np.int32), 2))
    w_tx = jnp.asarray(np.arange(B, dtype=np.int32))
    snap = jnp.zeros(B, jnp.int32)
    active = jnp.ones(B, bool)

    def whole(ks_rows, vs, bidx, rb, re_, wb, we):
        return kern(
            ks_rows, vs, bidx, jnp.int32(CAP // 2), rb, re_, r_tx, wb, we, w_tx,
            snap, active, jnp.int32(1 << 20),
            cap=CAP, n_txn=B, n_read=R, n_write=Wn,
        )

    _force(whole(ks_rows, vs, bidx, rb, re_, wb, we))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        _force(whole(ks_rows, vs, bidx, rb, re_, wb, we))
        ts.append(time.perf_counter() - t0)
    print(
        f"  {'WHOLE resolve_core kernel':<42s} "
        f"{sorted(ts)[1] * 1e3 - _RTT_MS[0]:9.2f} ms"
    )


if __name__ == "__main__":
    if "--phase" in sys.argv:
        phase_main()
    else:
        main()
