"""Per-phase accounting for the device conflict kernel on real hardware —
the analog of skipListTest's sort/combine/checkRead/merge PerfCounters
(fdbserver/SkipList.cpp:1412-1502).

Runs CUMULATIVE truncations of resolve_core (search | +history | +intra |
full) at bench.py shapes on a prefilled state; each truncation returns one
scalar digest so tunnel transfer cost never pollutes the timing (the axon
tunnel moves whole arrays at ~45 MB/s; block_until_ready does not block).
Phase cost = difference between successive truncations.

`collect()` returns the whole report as a dict; `--json [PATH]` emits it as
a machine-readable artifact (schema: control/status.py PHASE_PROFILE_SCHEMA).
bench.py embeds the same dict as `kernel.phase_profile` in BENCH output, so
phase regressions are artifact-visible instead of probe.log-only.
"""

from __future__ import annotations

import functools
import json
import sys
import time

import numpy as np

import bench as B


def collect(*, small: bool = False) -> dict:
    """Measure every phase and return the report dict.

    small=True shrinks state capacities and repetitions for the embedded
    bench.py --cpu-phase run (budgeted by BENCH_CPU_PHASE_TIMEOUT); the
    full-size run is the probe.log / BENCH_r* artifact."""
    B._enable_compile_cache()  # the ~20 truncation compiles persist for reuse
    import jax
    import jax.numpy as jnp

    from foundationdb_tpu.conflict import device as D

    cap = (1 << 15) if small else B.CAP
    rec_cap = (1 << 12) if small else B.REC_CAP
    prefill_n = 4 if small else B.PREFILL_BATCHES
    reps = 3 if small else 5

    out: dict = {
        "backend": jax.default_backend(),
        "small": small,
        "cap": cap,
        "rec_cap": rec_cap,
        "merge_impl_default": D._IMPL_DEFAULTS["merge"],
    }
    print(f"backend: {out['backend']}", flush=True)

    rng = np.random.default_rng(B.SEED)
    pool = B.gen_pool(rng)
    pool_words = B.pool_to_words(pool)
    versions = iter(range(1, 10_000))
    prefill = [B.gen_batch(rng, pool, next(versions)) for _ in range(prefill_n)]
    timed = [B.gen_batch(rng, pool, next(versions)) for _ in range(4)]

    dev = D.DeviceConflictSet(max_key_bytes=B.MAX_KEY_BYTES, capacity=cap)
    t0 = time.perf_counter()
    for b in prefill:
        dev.resolve_arrays(b["version"], *B.device_pack(pool_words, b, B._bucket))
    print(
        f"prefill {time.perf_counter() - t0:.1f}s, live boundaries {dev.boundary_count}",
        flush=True,
    )

    args0 = B.device_pack(pool_words, timed[0], B._bucket)
    rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p = [jnp.asarray(a) for a in args0]
    Bp, R, Wn = snap_p.shape[0], rbv.shape[0], wbv.shape[0]
    commit_off = jnp.int32(dev._offset(timed[0]["version"]))
    cap = dev._cap
    out["shapes"] = {"n_txn": Bp, "n_read": R, "n_write": Wn, "cap": cap}

    def common(ks, vs, bidx, count):
        r_ok = rtv >= 0
        r_idx = jnp.clip(rtv, 0, Bp - 1)
        w_ok = (wtv >= 0) & ~D._is_sentinel(wbv)
        w_idx = jnp.clip(wtv, 0, Bp - 1)
        return r_ok, r_idx, w_ok, w_idx

    @jax.jit
    def t_search(ks, vs, bidx, count):
        r_ok, r_idx, w_ok, w_idx = common(ks, vs, bidx, count)
        g_lo, g_hi, wbr, wer, conv = D.phase_search(
            ks, bidx, count, rbv, rev, wbv, wev, r_ok, w_ok, D.FAST_SEARCH_ITERS
        )
        return g_lo.sum() + g_hi.sum() + wbr.sum() + wer.sum()

    @jax.jit
    def t_hist(ks, vs, bidx, count):
        r_ok, r_idx, w_ok, w_idx = common(ks, vs, bidx, count)
        g_lo, g_hi, wbr, wer, conv = D.phase_search(
            ks, bidx, count, rbv, rev, wbv, wev, r_ok, w_ok, D.FAST_SEARCH_ITERS
        )
        hist = D.phase_history(vs, g_lo, g_hi, snap_p, r_idx, r_ok, Bp)
        return g_lo.sum() + hist.sum()

    @jax.jit
    def t_intra(ks, vs, bidx, count):
        r_ok, r_idx, w_ok, w_idx = common(ks, vs, bidx, count)
        g_lo, g_hi, wbr, wer, conv = D.phase_search(
            ks, bidx, count, rbv, rev, wbv, wev, r_ok, w_ok, D.FAST_SEARCH_ITERS
        )
        hist = D.phase_history(vs, g_lo, g_hi, snap_p, r_idx, r_ok, Bp)
        intra, n_iters = D.phase_intra(
            rbv, rev, wbv, wev, r_ok, w_ok, r_idx, w_idx, wtv, active_p,
            hist, Bp,
        )
        return g_lo.sum() + hist.sum() + intra.sum(), n_iters

    full = functools.partial(
        jax.jit, static_argnames=("cap", "n_txn", "n_read", "n_write", "search_iters")
    )(D.resolve_core)

    @jax.jit
    def t_full(ks, vs, bidx, count):
        verdict, nks, nvs, ncount, nbidx, conv, ok = full(
            ks, vs, bidx, count, rbv, rev, rtv, wbv, wev, wtv,
            snap_p, active_p, commit_off,
            cap=cap, n_txn=Bp, n_read=R, n_write=Wn,
        )
        return verdict.sum() + ncount + nbidx[0]

    st = (dev._ks, dev._vs, dev._bidx, dev._dev_count)

    def fetch(o):
        return np.asarray(jax.tree_util.tree_leaves(o)[0]).ravel()[:1]

    # RTT floor
    g = jax.jit(lambda v: v + 1)
    fetch(g(jnp.ones((8,), jnp.int32)))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        fetch(g(jnp.ones((8,), jnp.int32)))
        ts.append(time.perf_counter() - t0)
    rtt = sorted(ts)[2] * 1e3
    out["rtt_ms"] = rtt
    print(f"RTT floor {rtt:.1f} ms", flush=True)

    results = {}
    for name, fn in (("search", t_search), ("search+hist", t_hist),
                     ("search+hist+intra", t_intra), ("FULL kernel", t_full)):
        fetch(fn(*st))  # compile
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            o = fn(*st)
            fetch(o)
            ts.append(time.perf_counter() - t0)
        ms = sorted(ts)[len(ts) // 2] * 1e3 - rtt
        results[name] = ms
        extra = ""
        if name == "search+hist+intra":
            out["intra_iters"] = int(np.asarray(o[1]))
            extra = f"  (fixpoint iters: {out['intra_iters']})"
        print(f"  {name:<22s} {ms:9.1f} ms{extra}", flush=True)

    s = results
    out["cumulative_ms"] = {k: round(v, 2) for k, v in results.items()}
    out["phases_ms"] = {
        "search": round(s["search"], 2),
        "history": round(s["search+hist"] - s["search"], 2),
        "intra": round(s["search+hist+intra"] - s["search+hist"], 2),
        "merge_buckets": round(s["FULL kernel"] - s["search+hist+intra"], 2),
        "full": round(s["FULL kernel"], 2),
    }
    print("\nphase deltas:", flush=True)
    print(f"  search          {s['search']:9.1f} ms")
    print(f"  history (RMQ)   {s['search+hist'] - s['search']:9.1f} ms")
    print(f"  intra fixpoint  {s['search+hist+intra'] - s['search+hist']:9.1f} ms")
    print(f"  merge+buckets   {s['FULL kernel'] - s['search+hist+intra']:9.1f} ms")

    # ---- LSM path: full kernel + amortized compaction --------------------
    ldev = D.DeviceConflictSet(
        max_key_bytes=B.MAX_KEY_BYTES, capacity=cap, lsm=True,
        recent_capacity=rec_cap,
    )
    t0 = time.perf_counter()
    for b in prefill:
        ldev.resolve_arrays(b["version"], *B.device_pack(pool_words, b, B._bucket))
    print(f"\nLSM prefill {time.perf_counter() - t0:.1f}s "
          f"(compactions: {ldev.compactions})", flush=True)

    lfull = functools.partial(
        jax.jit,
        static_argnames=("cap", "rec_cap", "n_txn", "n_read", "n_write",
                         "search_iters", "rec_iters", "search_impl",
                         "merge_impl"),
    )(D.resolve_core_lsm)

    @jax.jit
    def t_lsm(ks, vs, tab, bidx, count, rks, rvs, rbidx, rcnt):
        verdict, nrk, nrv, nrb, nrc, conv, ok = lfull(
            ks, vs, tab, bidx, count, rks, rvs, rbidx, rcnt,
            rbv, rev, rtv, wbv, wev, wtv, snap_p, active_p, commit_off,
            cap=cap, rec_cap=ldev._rec_cap, n_txn=Bp, n_read=R, n_write=Wn,
        )
        return verdict.sum() + nrc

    lst = (ldev._ks, ldev._vs, ldev._tab, ldev._bidx, ldev._dev_count,
           ldev._rec_ks, ldev._rec_vs, ldev._rec_bidx, ldev._rec_dev_count)
    fetch(t_lsm(*lst))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fetch(t_lsm(*lst))
        ts.append(time.perf_counter() - t0)
    lsm_ms = sorted(ts)[len(ts) // 2] * 1e3 - rtt
    print(f"  LSM FULL (no compact)  {lsm_ms:9.1f} ms", flush=True)

    comp = functools.partial(
        jax.jit, static_argnames=("cap", "merge_impl", "lowering")
    )(D.compact_lsm)

    @jax.jit
    def t_comp(ks, vs, rks, rvs):
        nks, nvs, nc, nb, nt = comp(ks, vs, rks, rvs, cap=cap)
        return nc + nb[0] + nt[0, 0]

    cst = (ldev._ks, ldev._vs, ldev._rec_ks, ldev._rec_vs)
    fetch(t_comp(*cst))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        fetch(t_comp(*cst))
        ts.append(time.perf_counter() - t0)
    comp_ms = sorted(ts)[1] * 1e3 - rtt
    batches_per_compact = max((rec_cap - 1) // (2 * Wn), 1)
    print(f"  LSM compaction         {comp_ms:9.1f} ms "
          f"(/{batches_per_compact} batches = "
          f"{comp_ms / batches_per_compact:.1f} ms amortized)", flush=True)
    print(f"  LSM effective/batch    {lsm_ms + comp_ms / batches_per_compact:9.1f} ms",
          flush=True)
    out["lsm"] = {
        "full_ms": round(lsm_ms, 2),
        "compact_ms": round(comp_ms, 2),
        "batches_per_compact": batches_per_compact,
        "effective_ms": round(lsm_ms + comp_ms / batches_per_compact, 2),
    }

    # ---- merge-impl shootout (the dominant phase, isolated) --------------
    # sort vs gather vs scatter at the RECENT-level shape (the per-batch
    # cost in LSM mode) and at full CAP (the non-LSM per-batch cost)
    print("\nmerge-impl shootout:", flush=True)
    out["merge_shootout_ms"] = {}
    r_ok = rtv >= 0
    w_ok = (wtv >= 0) & ~D._is_sentinel(wbv)
    for label, cap_m, ks_m, vs_m, cnt_m in (
        (f"recent 2^{rec_cap.bit_length() - 1}", ldev._rec_cap,
         ldev._rec_ks, ldev._rec_vs, ldev._rec_dev_count),
        (f"main   2^{cap.bit_length() - 1}", dev._cap,
         dev._ks, dev._vs, dev._dev_count),
    ):
        # ranks from the sort search (exact at any depth)
        @jax.jit
        def ranks_of(ks_, cnt_):
            _gl, _gh, wbr, wer, _c = D.phase_search_sort(
                ks_, cnt_, rbv, rev, wbv, wev, r_ok, w_ok
            )
            return wbr, wer

        wbr, wer = ranks_of(ks_m, cnt_m)
        out["merge_shootout_ms"][label.replace(" ", "")] = {}
        for impl in ("sort", "gather", "scatter"):
            fn = D._MERGE_IMPLS[impl]
            jfn = functools.partial(jax.jit, static_argnames=("cap",))(fn)

            def probe(ks_, vs_, wbr_, wer_):
                nk, nv, nc = jfn(
                    ks_, vs_, wbv, wev, wbr_, wer_, w_ok,
                    jnp.int32(1000), cap=cap_m,
                )
                return nc + nv[0] + nk[0, 0]

            pj = jax.jit(probe)
            try:
                fetch(pj(ks_m, vs_m, wbr, wer))  # compile
                ts = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    fetch(pj(ks_m, vs_m, wbr, wer))
                    ts.append(time.perf_counter() - t0)
                ms = sorted(ts)[len(ts) // 2] * 1e3 - rtt
                out["merge_shootout_ms"][label.replace(" ", "")][impl] = round(ms, 2)
                print(f"  {label} merge={impl:<8s} {ms:9.1f} ms", flush=True)
            except Exception as e:  # noqa: BLE001 — report and keep going
                print(f"  {label} merge={impl:<8s} FAILED: {e!r}", flush=True)
                out["merge_shootout_ms"][label.replace(" ", "")][impl] = None
    return out


def main() -> None:
    json_path = None
    small = "--small" in sys.argv
    if "--json" in sys.argv:
        i = sys.argv.index("--json")
        json_path = sys.argv[i + 1] if i + 1 < len(sys.argv) else "-"
    report = collect(small=small)
    if json_path is not None:
        payload = json.dumps(report, sort_keys=True)
        if json_path == "-":
            print(f"PHASE_PROFILE {payload}", flush=True)
        else:
            with open(json_path, "w") as f:
                f.write(payload + "\n")
            print(f"phase profile written to {json_path}", flush=True)


if __name__ == "__main__":
    main()
