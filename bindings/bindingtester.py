"""Binding conformance tester — the stack-machine spec every binding must
execute identically (reference bindings/bindingtester/bindingtester.py +
spec/: a seed-driven op stream interpreted by each language binding, with
the resulting stacks and observations diffed byte-for-byte).

`gen_ops(seed, n)` produces a randomized op stream over a small adversarial
keyspace; `StackMachine(driver).run(ops)` interprets it against any object
implementing the driver surface:

    new_txn() -> txn;  txn.set/get/clear_range/get_range/get_key/
    get_range_selector/atomic_add/commit/reset

and returns a DIGEST — the observation log plus the final stack.  Two
bindings conform iff their digests for the same seed are equal.  Commit
versions are never recorded raw (different clusters assign different
versions); only data observations are.

Drivers for the three shipped bindings live in tests/test_bindingtester.py:
the C ABI (ctypes -> libfdbtpu_c.so -> gateway), the pure-Python gateway
client, and the in-process client."""

from __future__ import annotations

import random

NOT_PRESENT = b"RESULT_NOT_PRESENT"


def gen_ops(seed: int, n: int = 120) -> list[tuple]:
    """Seed-driven op stream (the spec generator).  Keys live under bt/
    with adversarial shapes: empty suffixes, embedded NULs, shared
    prefixes, near-boundary bytes."""
    rng = random.Random(seed)

    def key() -> bytes:
        kind = rng.randrange(5)
        if kind == 0:
            return b"bt/"
        if kind == 1:
            return b"bt/\x00" + bytes([rng.randrange(4)])
        if kind == 2:
            return b"bt/" + bytes(rng.randrange(3) for _ in range(rng.randrange(1, 6)))
        if kind == 3:
            return b"bt/p" * rng.randrange(1, 4)
        return b"bt/\xfe" + bytes([rng.randrange(256)])

    ops: list[tuple] = []
    for _ in range(n):
        k = rng.randrange(14)
        if k < 2:
            ops.append(("PUSH", key()))
        elif k == 2:
            ops.append(("DUP",))  # empty-stack DUP is a no-op in the machine
        elif k == 3:
            ops.append(("SWAP",))
        elif k == 4:
            ops.append(("SET", key(), bytes(rng.randrange(5) for _ in range(rng.randrange(0, 9)))))
        elif k == 5:
            ops.append(("GET", key()))
        elif k == 6:
            ops.append(("CLEAR_RANGE", *sorted((key(), key()))))
        elif k == 7:
            ops.append(("GET_RANGE", *sorted((key(), key())), rng.randrange(1, 20)))
        elif k == 8:
            ops.append(("ATOMIC_ADD", key(), rng.randrange(-50, 50)))
        elif k == 9:
            if rng.random() < 0.5:
                # behavior-neutral here (no lock held / fences unused), but
                # every binding must accept and route the option
                ops.append(("SET_OPTION", rng.choice(
                    [b"lock_aware", b"causal_write_risky"]
                )))
            else:
                ops.append(("GET_STACK_TOP",))
        elif k == 10:
            ops.append(("COMMIT",))
        elif k == 11:
            ops.append(("RESET",))
        elif k == 12:
            # selector resolution: (key, or_equal, offset) spanning the
            # whole first_greater_or_equal family, with offsets that step
            # off either end of the keyspace (clamped to "" / "\xff" —
            # every binding must agree byte-for-byte)
            ops.append(("GET_KEY", key(), rng.randrange(2) == 1,
                        rng.randrange(-4, 5)))
        else:
            ops.append(("GET_RANGE_SELECTOR", *sorted((key(), key())),
                        rng.randrange(2) == 1, rng.randrange(-2, 3),
                        rng.randrange(2) == 1, rng.randrange(-2, 3),
                        rng.randrange(1, 20)))
    ops.append(("COMMIT",))
    ops.append(("GET_RANGE", b"bt/", b"bt0", 1000))  # final full scan
    return ops


class StackMachine:
    def __init__(self, driver) -> None:
        self.driver = driver
        self.stack: list[bytes] = []
        self.log: list = []

    def run(self, ops: list[tuple]) -> list:
        tr = self.driver.new_txn()
        for op in ops:
            kind = op[0]
            if kind == "PUSH":
                self.stack.append(op[1])
            elif kind == "DUP":
                if self.stack:
                    self.stack.append(self.stack[-1])
            elif kind == "SWAP":
                if len(self.stack) >= 2:
                    self.stack[-1], self.stack[-2] = self.stack[-2], self.stack[-1]
            elif kind == "SET":
                tr.set(op[1], op[2])
            elif kind == "GET":
                v = tr.get(op[1])
                self.stack.append(v if v is not None else NOT_PRESENT)
            elif kind == "CLEAR_RANGE":
                tr.clear_range(op[1], op[2])
            elif kind == "GET_RANGE":
                rows = tr.get_range(op[1], op[2], op[3])
                packed = b";".join(k + b"=" + v for k, v in rows)
                self.stack.append(packed)
                self.log.append(("range", op[1], op[2], op[3], packed))
            elif kind == "GET_KEY":
                resolved = tr.get_key(op[1], op[2], op[3])
                self.stack.append(resolved)
                self.log.append(("getkey", resolved))
            elif kind == "GET_RANGE_SELECTOR":
                rows = tr.get_range_selector(
                    op[1], op[3], op[4], op[2], op[5], op[6], op[7]
                )
                packed = b";".join(k + b"=" + v for k, v in rows)
                self.stack.append(packed)
                self.log.append(("rangesel", packed))
            elif kind == "ATOMIC_ADD":
                tr.atomic_add(op[1], op[2])
            elif kind == "SET_OPTION":
                tr.set_option(op[1])
            elif kind == "GET_STACK_TOP":
                self.log.append(("top", self.stack[-1] if self.stack else b"EMPTY"))
            elif kind == "COMMIT":
                tr.commit()
                tr = self.driver.new_txn()
            elif kind == "RESET":
                tr.reset()
            else:
                raise ValueError(f"unknown op {kind!r}")
        tr.commit()
        return self.log + [("stack", list(self.stack))]


def digest(driver, seed: int, n: int = 120) -> list:
    return StackMachine(driver).run(gen_ops(seed, n))
