package FdbTpu;
# Perl binding for foundationdb_tpu over the gateway wire protocol
# (tools/gateway.py; the script-bindings slot of the reference's
# bindings/ruby — a pure-socket client, no compiled library).
#
#   my $db = FdbTpu->new("127.0.0.1", $port);
#   my $tid = $db->new_txn;
#   $db->set($tid, "k", "v");
#   $db->commit($tid);
#
# All methods die with "fdbtpu error <status>" on a non-zero status;
# codes 1..5 are retryable (pass to on_error and re-run).
use strict;
use warnings;
use IO::Socket::INET;

sub new {
    my ($class, $host, $port) = @_;
    my $sock = IO::Socket::INET->new(
        PeerAddr => $host, PeerPort => $port, Proto => 'tcp',
    ) or die "connect $host:$port failed: $!";
    binmode($sock);
    return bless { sock => $sock, req => 0 }, $class;
}

sub _call {
    my ($self, $op, $body) = @_;
    $body //= '';
    my $req = ++$self->{req};
    my $payload = pack('Q<C', $req, $op) . $body;
    my $frame = pack('V', length $payload) . $payload;
    my $s = $self->{sock};
    print {$s} $frame;
    my $hdr = $self->_read(4);
    my ($flen) = unpack('V', $hdr);
    my $reply = $self->_read($flen);
    my ($rid, $status) = unpack('Q<C', $reply);
    die "fdbtpu protocol error: reply id $rid != $req" if $rid != $req;
    die "fdbtpu error $status\n" if $status != 0;
    return substr($reply, 9);
}

sub _read {
    my ($self, $n) = @_;
    my $buf = '';
    while (length($buf) < $n) {
        my $got = sysread($self->{sock}, my $chunk, $n - length($buf));
        die "fdbtpu connection closed" unless $got;
        $buf .= $chunk;
    }
    return $buf;
}

sub _wstr { my ($s) = @_; return pack('V', length $s) . $s; }

sub protocol_version {
    my ($self) = @_;
    return unpack('V', $self->_call(12));
}

sub new_txn {
    my ($self) = @_;
    return unpack('Q<', $self->_call(1));
}

sub destroy_txn { my ($self, $t) = @_; $self->_call(2, pack('Q<', $t)); }
sub reset_txn   { my ($self, $t) = @_; $self->_call(3, pack('Q<', $t)); }

sub set {
    my ($self, $t, $k, $v) = @_;
    $self->_call(4, pack('Q<', $t) . _wstr($k) . _wstr($v));
}

sub clear_range {
    my ($self, $t, $b, $e) = @_;
    $self->_call(5, pack('Q<', $t) . _wstr($b) . _wstr($e));
}

sub get {
    my ($self, $t, $k) = @_;
    my $out = $self->_call(6, pack('Q<', $t) . _wstr($k));
    my $present = unpack('C', $out);
    my ($len) = unpack('V', substr($out, 1, 4));
    return $present ? substr($out, 5, $len) : undef;
}

sub get_range {
    my ($self, $t, $b, $e, $limit) = @_;
    $limit //= 10000;
    my $out = $self->_call(
        7, pack('Q<', $t) . _wstr($b) . _wstr($e) . pack('V', $limit));
    my ($n) = unpack('V', $out);
    my $off = 4;
    my @rows;
    for (1 .. $n) {
        my ($kl) = unpack('V', substr($out, $off, 4)); $off += 4;
        my $k = substr($out, $off, $kl); $off += $kl;
        my ($vl) = unpack('V', substr($out, $off, 4)); $off += 4;
        my $v = substr($out, $off, $vl); $off += $vl;
        push @rows, [$k, $v];
    }
    return \@rows;
}

# wire KeySelector: length-prefixed key, u8 or_equal, i32 offset
sub _wsel {
    my ($k, $or_equal, $offset) = @_;
    return _wstr($k) . pack('C l<', $or_equal ? 1 : 0, $offset);
}

# Resolve a KeySelector server-side (GET_KEY, op 15); args (key, or_equal,
# offset) default to first_greater_or_equal(key).  Offset overflow clamps
# to the keyspace boundary ("" / "\xff") — docs/API.md.
sub get_key {
    my ($self, $t, $k, $or_equal, $offset) = @_;
    $or_equal //= 0;
    $offset   //= 1;
    my $out = $self->_call(15, pack('Q<', $t) . _wsel($k, $or_equal, $offset));
    my ($len) = unpack('V', $out);
    return substr($out, 4, $len);
}

sub _parse_rows {
    my ($out) = @_;
    my ($n) = unpack('V', $out);
    my $off = 4;
    my @rows;
    for (1 .. $n) {
        my ($kl) = unpack('V', substr($out, $off, 4)); $off += 4;
        my $k = substr($out, $off, $kl); $off += $kl;
        my ($vl) = unpack('V', substr($out, $off, 4)); $off += 4;
        my $v = substr($out, $off, $vl); $off += $vl;
        push @rows, [$k, $v];
    }
    return \@rows;
}

# Range read with KeySelector endpoints (GET_RANGE_SELECTOR, op 16).
sub get_range_selector {
    my ($self, $t, $bk, $boe, $boff, $ek, $eoe, $eoff, $limit) = @_;
    $limit //= 10000;
    my $out = $self->_call(
        16, pack('Q<', $t) . _wsel($bk, $boe, $boff) . _wsel($ek, $eoe, $eoff)
            . pack('V', $limit));
    return _parse_rows($out);
}

sub atomic_add {
    my ($self, $t, $k, $delta) = @_;
    $self->_call(10, pack('Q<', $t) . _wstr($k) . pack('q<', $delta));
}

sub commit {
    my ($self, $t) = @_;
    return unpack('q<', $self->_call(8, pack('Q<', $t)));
}

sub on_error {
    my ($self, $t, $code) = @_;
    $self->_call(9, pack('Q<', $t) . pack('l<', $code));
}

sub set_option {
    my ($self, $t, $opt) = @_;
    $self->_call(13, pack('Q<', $t) . _wstr($opt));
}

sub get_read_version {
    my ($self, $t) = @_;
    return unpack('q<', $self->_call(11, pack('Q<', $t)));
}

# BLOCKS this connection until the key's value changes; returns the
# firing version (use a dedicated FdbTpu connection for watches).
sub watch {
    my ($self, $t, $k) = @_;
    return unpack('q<', $self->_call(14, pack('Q<', $t) . _wstr($k)));
}

sub close { my ($self) = @_; close($self->{sock}); }

1;
