#!/usr/bin/perl
# Perl side of the cross-binding stack-machine conformance tester
# (bindings/bindingtester.py): reads {host, port, ops} as JSON on stdin
# (byte fields base64), executes the SAME stack-machine semantics against
# the gateway, and prints its digest as JSON on stdout.  Divergence from
# another binding's digest = nonconformance.
use strict;
use warnings;
use FindBin;
use lib $FindBin::Bin;
use FdbTpu;
use JSON::PP;
use MIME::Base64 qw(decode_base64 encode_base64);

my $input = do { local $/; <STDIN> };
my $spec = JSON::PP->new->decode($input);
my $db = FdbTpu->new($spec->{host}, $spec->{port});

my @stack;
my @log;
my $NOT_PRESENT = 'RESULT_NOT_PRESENT';

sub b64 { my ($s) = @_; my $e = encode_base64($s, ''); return $e; }

my $t = $db->new_txn;
for my $op (@{ $spec->{ops} }) {
    my ($kind, @args) = @$op;
    if ($kind eq 'PUSH') {
        push @stack, decode_base64($args[0]);
    } elsif ($kind eq 'DUP') {
        push @stack, $stack[-1] if @stack;
    } elsif ($kind eq 'SWAP') {
        @stack[-1, -2] = @stack[-2, -1] if @stack >= 2;
    } elsif ($kind eq 'SET') {
        $db->set($t, decode_base64($args[0]), decode_base64($args[1]));
    } elsif ($kind eq 'GET') {
        my $v = $db->get($t, decode_base64($args[0]));
        push @stack, defined($v) ? $v : $NOT_PRESENT;
    } elsif ($kind eq 'CLEAR_RANGE') {
        $db->clear_range($t, decode_base64($args[0]), decode_base64($args[1]));
    } elsif ($kind eq 'GET_RANGE') {
        my $rows = $db->get_range(
            $t, decode_base64($args[0]), decode_base64($args[1]), $args[2]);
        my $packed = join(';', map { $_->[0] . '=' . $_->[1] } @$rows);
        push @stack, $packed;
        push @log, ['range', $args[0], $args[1], $args[2], b64($packed)];
    } elsif ($kind eq 'GET_KEY') {
        my $resolved = $db->get_key(
            $t, decode_base64($args[0]), $args[1], $args[2]);
        push @stack, $resolved;
        push @log, ['getkey', b64($resolved)];
    } elsif ($kind eq 'GET_RANGE_SELECTOR') {
        my $rows = $db->get_range_selector(
            $t, decode_base64($args[0]), $args[1], $args[2],
            decode_base64($args[3]), $args[4], $args[5], $args[6]);
        my $packed = join(';', map { $_->[0] . '=' . $_->[1] } @$rows);
        push @stack, $packed;
        push @log, ['rangesel', b64($packed)];
    } elsif ($kind eq 'ATOMIC_ADD') {
        $db->atomic_add($t, decode_base64($args[0]), $args[1]);
    } elsif ($kind eq 'SET_OPTION') {
        $db->set_option($t, decode_base64($args[0]));
    } elsif ($kind eq 'GET_STACK_TOP') {
        push @log, ['top', @stack ? b64($stack[-1]) : b64('EMPTY')];
    } elsif ($kind eq 'COMMIT') {
        $db->commit($t);
        $t = $db->new_txn;
    } elsif ($kind eq 'RESET') {
        $db->reset_txn($t);
    } else {
        die "unknown op $kind";
    }
}
$db->commit($t);
push @log, ['stack', [map { b64($_) } @stack]];
print JSON::PP->new->canonical->encode(\@log), "\n";
