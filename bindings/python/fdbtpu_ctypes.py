"""Python binding over the C client ABI — proof that libfdbtpu_c.so serves
any FFI-capable language (the script-bindings slot: reference
bindings/python/fdb/impl.py wraps fdb_c the same way).

Usage:
    db = FdbTpu("libfdbtpu_c.so", host, port)
    with db.transaction() as tr:
        tr[b"k"] = b"v"
    # commit on clean exit, on_error+retry on retryable failures
"""

from __future__ import annotations

import ctypes


class FdbTpuError(Exception):
    def __init__(self, code: int) -> None:
        super().__init__(f"fdbtpu error {code}")
        self.code = code


class _Txn:
    def __init__(self, db: "FdbTpu", tid: int) -> None:
        self._db = db
        self._tid = tid

    def set(self, key: bytes, value: bytes) -> None:
        self._db._check(
            self._db._lib.fdbtpu_txn_set(
                self._db._h, self._tid, key, len(key), value, len(value)
            )
        )

    __setitem__ = set

    def get(self, key: bytes) -> bytes | None:
        present = ctypes.c_int()
        val = ctypes.POINTER(ctypes.c_uint8)()
        vlen = ctypes.c_uint32()
        self._db._check(
            self._db._lib.fdbtpu_txn_get(
                self._db._h, self._tid, key, len(key),
                ctypes.byref(present), ctypes.byref(val), ctypes.byref(vlen),
            )
        )
        if not present.value:
            return None
        out = bytes(bytearray(val[i] for i in range(vlen.value)))
        self._db._libc.free(val)
        return out

    def __getitem__(self, key: bytes) -> bytes | None:
        return self.get(key)

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self._db._check(
            self._db._lib.fdbtpu_txn_clear_range(
                self._db._h, self._tid, begin, len(begin), end, len(end)
            )
        )

    def atomic_add(self, key: bytes, delta: int) -> None:
        self._db._check(
            self._db._lib.fdbtpu_txn_atomic_add(
                self._db._h, self._tid, key, len(key), delta
            )
        )

    def _take_rows(self, n, blob, blob_len):
        """Copy out + free a malloc'd row blob (u32 klen, key, u32 vlen,
        value — the layout every range-shaped C call replies with)."""
        raw = bytes(bytearray(blob[i] for i in range(blob_len.value)))
        if blob_len.value:
            self._db._libc.free(blob)
        rows, off = [], 0
        for _ in range(n.value):
            klen = int.from_bytes(raw[off : off + 4], "little")
            off += 4
            k = raw[off : off + klen]
            off += klen
            vlen = int.from_bytes(raw[off : off + 4], "little")
            off += 4
            rows.append((k, raw[off : off + vlen]))
            off += vlen
        return rows

    def get_range(self, begin: bytes, end: bytes, limit: int = 10000):
        n = ctypes.c_uint32()
        blob = ctypes.POINTER(ctypes.c_uint8)()
        blob_len = ctypes.c_uint32()
        self._db._check(
            self._db._lib.fdbtpu_txn_get_range(
                self._db._h, self._tid, begin, len(begin), end, len(end),
                limit, ctypes.byref(n), ctypes.byref(blob), ctypes.byref(blob_len),
            )
        )
        return self._take_rows(n, blob, blob_len)

    def get_key(self, key: bytes, or_equal: bool = False,
                offset: int = 1) -> bytes:
        """Resolve a KeySelector (fdb_transaction_get_key); defaults are
        first_greater_or_equal(key).  Offset overflow clamps to the
        keyspace boundary (b"" / b"\\xff") — docs/API.md."""
        resolved = ctypes.POINTER(ctypes.c_uint8)()
        rlen = ctypes.c_uint32()
        self._db._check(
            self._db._lib.fdbtpu_txn_get_key(
                self._db._h, self._tid, key, len(key),
                1 if or_equal else 0, offset,
                ctypes.byref(resolved), ctypes.byref(rlen),
            )
        )
        out = bytes(bytearray(resolved[i] for i in range(rlen.value)))
        if resolved:
            self._db._libc.free(resolved)
        return out

    def get_range_selector(self, begin_key: bytes, begin_or_equal: bool,
                           begin_offset: int, end_key: bytes,
                           end_or_equal: bool, end_offset: int,
                           limit: int = 10000):
        """Range read with KeySelector endpoints (blob layout shared with
        get_range)."""
        n = ctypes.c_uint32()
        blob = ctypes.POINTER(ctypes.c_uint8)()
        blob_len = ctypes.c_uint32()
        self._db._check(
            self._db._lib.fdbtpu_txn_get_range_selector(
                self._db._h, self._tid,
                begin_key, len(begin_key), 1 if begin_or_equal else 0,
                begin_offset,
                end_key, len(end_key), 1 if end_or_equal else 0, end_offset,
                limit, ctypes.byref(n), ctypes.byref(blob),
                ctypes.byref(blob_len),
            )
        )
        return self._take_rows(n, blob, blob_len)

    def commit(self) -> int:
        version = ctypes.c_int64()
        self._db._check(
            self._db._lib.fdbtpu_txn_commit(
                self._db._h, self._tid, ctypes.byref(version)
            )
        )
        return version.value

    def on_error(self, code: int) -> None:
        rc = self._db._lib.fdbtpu_txn_on_error(self._db._h, self._tid, code)
        if rc != 0:
            raise FdbTpuError(rc)

    def reset(self) -> None:
        self._db._check(self._db._lib.fdbtpu_txn_reset(self._db._h, self._tid))

    def set_option(self, option: bytes) -> None:
        self._db._check(
            self._db._lib.fdbtpu_txn_set_option(
                self._db._h, self._tid, option, len(option)
            )
        )

    def watch(self, key: bytes) -> int:
        """Blocks this handle until key's value changes; returns the
        firing version (use a dedicated FdbTpu connection for watches)."""
        version = ctypes.c_int64()
        self._db._check(
            self._db._lib.fdbtpu_txn_watch(
                self._db._h, self._tid, key, len(key), ctypes.byref(version)
            )
        )
        return version.value

    def destroy(self) -> None:
        self._db._lib.fdbtpu_txn_destroy(self._db._h, self._tid)


class FdbTpu:
    def __init__(self, libpath: str, host: str, port: int) -> None:
        self._lib = lib = ctypes.CDLL(libpath)
        self._libc = ctypes.CDLL(None)
        C = ctypes
        u8p, u32, u64, i64 = (
            C.POINTER(C.c_uint8), C.c_uint32, C.c_uint64, C.c_int64
        )
        lib.fdbtpu_open.restype = C.c_void_p
        lib.fdbtpu_open.argtypes = [C.c_char_p, C.c_int]
        lib.fdbtpu_close.argtypes = [C.c_void_p]
        lib.fdbtpu_txn_create.argtypes = [C.c_void_p, C.POINTER(u64)]
        for name in ("fdbtpu_txn_destroy", "fdbtpu_txn_reset"):
            getattr(lib, name).argtypes = [C.c_void_p, u64]
        lib.fdbtpu_txn_set.argtypes = [C.c_void_p, u64, C.c_char_p, u32,
                                       C.c_char_p, u32]
        lib.fdbtpu_txn_clear_range.argtypes = [C.c_void_p, u64, C.c_char_p,
                                               u32, C.c_char_p, u32]
        lib.fdbtpu_txn_atomic_add.argtypes = [C.c_void_p, u64, C.c_char_p,
                                              u32, i64]
        lib.fdbtpu_txn_set_option.argtypes = [C.c_void_p, u64, C.c_char_p, u32]
        lib.fdbtpu_txn_watch.argtypes = [C.c_void_p, u64, C.c_char_p, u32,
                                         C.POINTER(i64)]
        lib.fdbtpu_txn_get.argtypes = [C.c_void_p, u64, C.c_char_p, u32,
                                       C.POINTER(C.c_int), C.POINTER(u8p),
                                       C.POINTER(u32)]
        lib.fdbtpu_txn_get_range.argtypes = [
            C.c_void_p, u64, C.c_char_p, u32, C.c_char_p, u32, u32,
            C.POINTER(u32), C.POINTER(u8p), C.POINTER(u32),
        ]
        lib.fdbtpu_txn_get_key.argtypes = [
            C.c_void_p, u64, C.c_char_p, u32, C.c_int, C.c_int32,
            C.POINTER(u8p), C.POINTER(u32),
        ]
        lib.fdbtpu_txn_get_range_selector.argtypes = [
            C.c_void_p, u64, C.c_char_p, u32, C.c_int, C.c_int32,
            C.c_char_p, u32, C.c_int, C.c_int32, u32,
            C.POINTER(u32), C.POINTER(u8p), C.POINTER(u32),
        ]
        lib.fdbtpu_txn_commit.argtypes = [C.c_void_p, u64, C.POINTER(i64)]
        lib.fdbtpu_txn_get_read_version.argtypes = [C.c_void_p, u64,
                                                    C.POINTER(i64)]
        lib.fdbtpu_txn_on_error.argtypes = [C.c_void_p, u64, C.c_int]
        self._libc.free.argtypes = [C.c_void_p]
        self._h = C.c_void_p(lib.fdbtpu_open(host.encode(), port))
        if not self._h:
            raise FdbTpuError(-1)

    @staticmethod
    def _check(code: int) -> None:
        if code != 0:
            raise FdbTpuError(code)

    def create_transaction(self) -> _Txn:
        tid = ctypes.c_uint64()
        self._check(self._lib.fdbtpu_txn_create(self._h, ctypes.byref(tid)))
        return _Txn(self, tid.value)

    def run(self, fn):
        """The fdb.transactional retry loop over the C ABI."""
        tr = self.create_transaction()
        try:
            while True:
                try:
                    out = fn(tr)
                    tr.commit()
                    return out
                except FdbTpuError as e:
                    tr.on_error(e.code)  # raises when not retryable
        finally:
            tr.destroy()

    def close(self) -> None:
        self._lib.fdbtpu_close(self._h)
