/* C smoke driver for the fdbtpu C ABI: the transactional basics a C caller
 * needs — set/get/commit, read-your-writes, clear_range, atomic add, the
 * on_error retry loop — against a live gateway.  Run by
 * tests/test_c_bindings.py; prints "C-OK <committed_version>" on success. */
#include "fdbtpu_c.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define CHECK(cond, msg)                                                       \
  do {                                                                         \
    if (!(cond)) {                                                             \
      fprintf(stderr, "FAIL: %s\n", msg);                                      \
      return 1;                                                                \
    }                                                                          \
  } while (0)

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: ctest HOST PORT\n");
    return 2;
  }
  FDBTPU_Database *db = fdbtpu_open(argv[1], atoi(argv[2]));
  CHECK(db != NULL, "connect");

  uint64_t txn;
  int64_t version = -1;
  for (;;) {
    CHECK(fdbtpu_txn_create(db, &txn) == 0, "txn_create");
    int st = fdbtpu_txn_set(db, txn, (const uint8_t *)"c/one", 5,
                            (const uint8_t *)"1", 1);
    if (st == 0)
      st = fdbtpu_txn_set(db, txn, (const uint8_t *)"c/two", 5,
                          (const uint8_t *)"2", 1);
    if (st == 0)
      st = fdbtpu_txn_atomic_add(db, txn, (const uint8_t *)"c/ctr", 5, 40);
    /* read-your-writes before commit */
    if (st == 0) {
      int present;
      uint8_t *val;
      uint32_t vlen;
      st = fdbtpu_txn_get(db, txn, (const uint8_t *)"c/one", 5, &present, &val,
                          &vlen);
      if (st == 0) {
        CHECK(present == 1 && vlen == 1 && val[0] == '1', "RYW get");
        free(val);
      }
    }
    if (st == 0) st = fdbtpu_txn_commit(db, txn, &version);
    fdbtpu_txn_destroy(db, txn);
    if (st == 0) break;
    CHECK(fdbtpu_txn_on_error(db, txn, st) == 0, "non-retryable error");
  }
  CHECK(version > 0, "commit version");

  /* second transaction: atomic add again + clear one key, verify reads */
  for (;;) {
    CHECK(fdbtpu_txn_create(db, &txn) == 0, "txn2_create");
    int st = fdbtpu_txn_atomic_add(db, txn, (const uint8_t *)"c/ctr", 5, 2);
    if (st == 0)
      st = fdbtpu_txn_clear_range(db, txn, (const uint8_t *)"c/two", 5,
                                  (const uint8_t *)"c/two\x00", 6);
    int64_t commit2;
    if (st == 0) st = fdbtpu_txn_commit(db, txn, &commit2);
    fdbtpu_txn_destroy(db, txn);
    if (st == 0) break;
    CHECK(fdbtpu_txn_on_error(db, txn, st) == 0, "txn2 non-retryable");
  }

  /* verification transaction */
  CHECK(fdbtpu_txn_create(db, &txn) == 0, "txn3_create");
  {
    int present;
    uint8_t *val;
    uint32_t vlen;
    CHECK(fdbtpu_txn_get(db, txn, (const uint8_t *)"c/two", 5, &present, &val,
                         &vlen) == 0,
          "get two");
    CHECK(present == 0, "c/two cleared");
    CHECK(fdbtpu_txn_get(db, txn, (const uint8_t *)"c/ctr", 5, &present, &val,
                         &vlen) == 0,
          "get ctr");
    CHECK(present == 1 && vlen == 8, "ctr present");
    int64_t ctr;
    memcpy(&ctr, val, 8);
    free(val);
    CHECK(ctr == 42, "atomic adds sum to 42");

    uint32_t n_rows, blob_len;
    uint8_t *blob;
    CHECK(fdbtpu_txn_get_range(db, txn, (const uint8_t *)"c/", 2,
                               (const uint8_t *)"c0", 2, 100, &n_rows, &blob,
                               &blob_len) == 0,
          "get_range");
    CHECK(n_rows == 2, "range row count"); /* c/ctr, c/one */
    free(blob);

    /* key selectors resolve server-side: first_greater_or_equal("c/")
     * lands on c/ctr, first_greater_than("c/ctr") on c/one, and walking
     * past the last key clamps to the keyspace boundary "\xff" */
    {
      uint8_t *resolved;
      uint32_t rlen;
      CHECK(fdbtpu_txn_get_key(db, txn, (const uint8_t *)"c/", 2,
                               /*or_equal=*/0, /*offset=*/1, &resolved,
                               &rlen) == 0,
            "get_key fge");
      CHECK(rlen == 5 && memcmp(resolved, "c/ctr", 5) == 0, "fge resolves");
      free(resolved);
      CHECK(fdbtpu_txn_get_key(db, txn, (const uint8_t *)"c/ctr", 5,
                               /*or_equal=*/1, /*offset=*/1, &resolved,
                               &rlen) == 0,
            "get_key fgt");
      CHECK(rlen == 5 && memcmp(resolved, "c/one", 5) == 0, "fgt resolves");
      free(resolved);
      CHECK(fdbtpu_txn_get_key(db, txn, (const uint8_t *)"c/one", 5,
                               /*or_equal=*/1, /*offset=*/100, &resolved,
                               &rlen) == 0,
            "get_key overflow");
      CHECK(rlen == 1 && resolved[0] == 0xff, "overflow clamps to \\xff");
      free(resolved);

      uint32_t n_rows, blob_len;
      uint8_t *blob;
      CHECK(fdbtpu_txn_get_range_selector(
                db, txn, (const uint8_t *)"c/", 2, 0, 1,
                (const uint8_t *)"c/one", 5, 1, 1, 100, &n_rows, &blob,
                &blob_len) == 0,
            "get_range_selector");
      CHECK(n_rows == 2, "selector range rows"); /* c/ctr, c/one */
      free(blob);
    }

    /* transaction options route end to end (lock_aware on an unlocked
     * database is a no-op, an unknown option is refused) */
    CHECK(fdbtpu_txn_set_option(db, txn, (const uint8_t *)"lock_aware", 10) == 0,
          "set_option lock_aware");
    CHECK(fdbtpu_txn_set_option(db, txn, (const uint8_t *)"bogus", 5) != 0,
          "bogus option refused");
  }
  fdbtpu_txn_destroy(db, txn);
  fdbtpu_close(db);
  printf("C-OK %lld\n", (long long)version);
  return 0;
}
