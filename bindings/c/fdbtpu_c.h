/* fdbtpu C client ABI — the fdb_c.h analog (reference bindings/c/
 * foundationdb/fdb_c.h; implementation notes in fdbtpu_c.cpp).
 *
 * Blocking, thread-compatible-per-database handle.  Error codes match the
 * gateway protocol (foundationdb_tpu/tools/gateway.py):
 *   0 ok, 1 not_committed, 2 transaction_too_old, 3 commit_unknown_result,
 *   4 future_version, 5 timed_out, 6 bad_request, 255 internal,
 *   -1 connection failure.
 * Codes 1..5 are retryable: pass them to fdbtpu_txn_on_error and re-run
 * the transaction body (the fdb on_error loop).
 */
#ifndef FDBTPU_C_H
#define FDBTPU_C_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct FDBTPU_Database FDBTPU_Database;

FDBTPU_Database *fdbtpu_open(const char *host, int port);
void fdbtpu_close(FDBTPU_Database *db);

/* returns 0 on success; txn id out-param */
int fdbtpu_txn_create(FDBTPU_Database *db, uint64_t *txn);
int fdbtpu_txn_destroy(FDBTPU_Database *db, uint64_t txn);
int fdbtpu_txn_reset(FDBTPU_Database *db, uint64_t txn);

int fdbtpu_txn_set(FDBTPU_Database *db, uint64_t txn,
                   const uint8_t *key, uint32_t key_len,
                   const uint8_t *val, uint32_t val_len);
int fdbtpu_txn_clear_range(FDBTPU_Database *db, uint64_t txn,
                           const uint8_t *begin, uint32_t begin_len,
                           const uint8_t *end, uint32_t end_len);
/* transaction option by name (e.g. "lock_aware", "causal_write_risky") —
 * the vexillographer-generated option vocabulary of the python client */
int fdbtpu_txn_set_option(FDBTPU_Database *db, uint64_t txn,
                          const uint8_t *option, uint32_t option_len);

/* BLOCKS until key's value changes; returns the firing version
 * (fdb_transaction_watch).  The handle runs one request at a time, so
 * use a dedicated FDBTPU_Database for watches. */
int fdbtpu_txn_watch(FDBTPU_Database *db, uint64_t txn, const uint8_t *key,
                     uint32_t key_len, int64_t *version);

int fdbtpu_txn_atomic_add(FDBTPU_Database *db, uint64_t txn,
                          const uint8_t *key, uint32_t key_len, int64_t delta);

/* *present=0/1; on present, *val is malloc'd (caller frees), *val_len set */
int fdbtpu_txn_get(FDBTPU_Database *db, uint64_t txn,
                   const uint8_t *key, uint32_t key_len,
                   int *present, uint8_t **val, uint32_t *val_len);

/* rows returned as one malloc'd blob: n × (u32 klen, key, u32 vlen, val);
 * caller frees *blob */
int fdbtpu_txn_get_range(FDBTPU_Database *db, uint64_t txn,
                         const uint8_t *begin, uint32_t begin_len,
                         const uint8_t *end, uint32_t end_len,
                         uint32_t limit, uint32_t *n_rows,
                         uint8_t **blob, uint32_t *blob_len);

/* Resolve a KeySelector (fdb_transaction_get_key): (key, or_equal, offset)
 * in the first_greater_or_equal family; offset overflow clamps to the
 * keyspace boundary ("" / "\xff") instead of erroring.  *resolved is
 * malloc'd (caller frees; may be zero-length). */
int fdbtpu_txn_get_key(FDBTPU_Database *db, uint64_t txn,
                       const uint8_t *key, uint32_t key_len,
                       int or_equal, int32_t offset,
                       uint8_t **resolved, uint32_t *resolved_len);

/* Range read with KeySelector endpoints; blob layout as get_range. */
int fdbtpu_txn_get_range_selector(
    FDBTPU_Database *db, uint64_t txn,
    const uint8_t *bkey, uint32_t bkey_len, int b_or_equal, int32_t b_offset,
    const uint8_t *ekey, uint32_t ekey_len, int e_or_equal, int32_t e_offset,
    uint32_t limit, uint32_t *n_rows, uint8_t **blob, uint32_t *blob_len);

int fdbtpu_txn_commit(FDBTPU_Database *db, uint64_t txn, int64_t *version);
int fdbtpu_txn_get_read_version(FDBTPU_Database *db, uint64_t txn,
                                int64_t *version);

/* backoff + reset for a retryable code; returns 0 if the caller should
 * retry the body, else the (non-retryable) code */
int fdbtpu_txn_on_error(FDBTPU_Database *db, uint64_t txn, int code);

#ifdef __cplusplus
}
#endif
#endif /* FDBTPU_C_H */
