/* fdbtpu C client — blocking stub speaking the gateway's length-prefixed
 * binary protocol (foundationdb_tpu/tools/gateway.py; the fdb_c.cpp slot,
 * reference bindings/c/fdb_c.cpp:85-293).
 *
 * The reference links the entire native client into the caller; this
 * client keeps transactions server-side (read-your-writes objects in the
 * gateway) and the wire protocol language-neutral — the same .so serves C,
 * and any FFI-capable language (see bindings/python/fdbtpu_ctypes.py).
 *
 * One socket per database handle; requests are serialized on it (simple
 * blocking request/reply — a request id is carried for future pipelining).
 */
#include "fdbtpu_c.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

struct FDBTPU_Database {
  int fd;
  uint64_t next_req;
};

/* ---- little-endian buffer helpers ---- */
static void put_u32(uint8_t *p, uint32_t v) { memcpy(p, &v, 4); }
static void put_u64(uint8_t *p, uint64_t v) { memcpy(p, &v, 8); }
static void put_i64(uint8_t *p, int64_t v) { memcpy(p, &v, 8); }
static uint32_t get_u32(const uint8_t *p) { uint32_t v; memcpy(&v, p, 4); return v; }
static uint64_t get_u64(const uint8_t *p) { uint64_t v; memcpy(&v, p, 8); return v; }
static int64_t get_i64(const uint8_t *p) { int64_t v; memcpy(&v, p, 8); return v; }

static int write_all(int fd, const uint8_t *buf, size_t n) {
  while (n > 0) {
    ssize_t w = write(fd, buf, n);
    if (w <= 0) return -1;
    buf += w;
    n -= (size_t)w;
  }
  return 0;
}

static int read_all(int fd, uint8_t *buf, size_t n) {
  while (n > 0) {
    ssize_t r = read(fd, buf, n);
    if (r <= 0) return -1;
    buf += r;
    n -= (size_t)r;
  }
  return 0;
}

/* ---- request/reply ----
 * body is the op payload AFTER (req_id, op).  On success *out is a
 * malloc'd reply body (may be NULL when empty) and the status is
 * returned. */
static int rpc(FDBTPU_Database *db, uint8_t op, const uint8_t *body,
               uint32_t body_len, uint8_t **out, uint32_t *out_len) {
  uint64_t req = ++db->next_req;
  uint32_t flen = 8 + 1 + body_len;
  uint8_t hdr[4 + 8 + 1];
  put_u32(hdr, flen);
  put_u64(hdr + 4, req);
  hdr[12] = op;
  if (write_all(db->fd, hdr, sizeof hdr) != 0) return -1;
  if (body_len && write_all(db->fd, body, body_len) != 0) return -1;

  uint8_t rl[4];
  if (read_all(db->fd, rl, 4) != 0) return -1;
  uint32_t rlen = get_u32(rl);
  if (rlen < 9) return -1;
  uint8_t *rbuf = (uint8_t *)malloc(rlen);
  if (!rbuf) return -1;
  if (read_all(db->fd, rbuf, rlen) != 0) { free(rbuf); return -1; }
  if (get_u64(rbuf) != req) { free(rbuf); return -1; } /* no pipelining yet */
  int status = rbuf[8];
  if (out) {
    *out_len = rlen - 9;
    if (*out_len) {
      *out = (uint8_t *)malloc(*out_len);
      memcpy(*out, rbuf + 9, *out_len);
    } else {
      *out = NULL;
    }
  }
  free(rbuf);
  return status;
}

FDBTPU_Database *fdbtpu_open(const char *host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return NULL;
  struct sockaddr_in sa;
  memset(&sa, 0, sizeof sa);
  sa.sin_family = AF_INET;
  sa.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &sa.sin_addr) != 1 ||
      connect(fd, (struct sockaddr *)&sa, sizeof sa) != 0) {
    close(fd);
    return NULL;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  FDBTPU_Database *db = (FDBTPU_Database *)calloc(1, sizeof(FDBTPU_Database));
  db->fd = fd;
  return db;
}

void fdbtpu_close(FDBTPU_Database *db) {
  if (!db) return;
  close(db->fd);
  free(db);
}

int fdbtpu_txn_create(FDBTPU_Database *db, uint64_t *txn) {
  uint8_t *out = NULL;
  uint32_t out_len = 0;
  int st = rpc(db, 1, NULL, 0, &out, &out_len);
  if (st == 0 && out_len >= 8) *txn = get_u64(out);
  free(out);
  return st;
}

static int txn_only(FDBTPU_Database *db, uint8_t op, uint64_t txn) {
  uint8_t body[8];
  put_u64(body, txn);
  return rpc(db, op, body, 8, NULL, NULL);
}

int fdbtpu_txn_destroy(FDBTPU_Database *db, uint64_t txn) {
  return txn_only(db, 2, txn);
}
int fdbtpu_txn_reset(FDBTPU_Database *db, uint64_t txn) {
  return txn_only(db, 3, txn);
}

int fdbtpu_txn_set(FDBTPU_Database *db, uint64_t txn, const uint8_t *key,
                   uint32_t key_len, const uint8_t *val, uint32_t val_len) {
  uint32_t blen = 8 + 4 + key_len + 4 + val_len;
  uint8_t *b = (uint8_t *)malloc(blen);
  put_u64(b, txn);
  put_u32(b + 8, key_len);
  memcpy(b + 12, key, key_len);
  put_u32(b + 12 + key_len, val_len);
  memcpy(b + 16 + key_len, val, val_len);
  int st = rpc(db, 4, b, blen, NULL, NULL);
  free(b);
  return st;
}

int fdbtpu_txn_clear_range(FDBTPU_Database *db, uint64_t txn,
                           const uint8_t *begin, uint32_t begin_len,
                           const uint8_t *end, uint32_t end_len) {
  uint32_t blen = 8 + 4 + begin_len + 4 + end_len;
  uint8_t *b = (uint8_t *)malloc(blen);
  put_u64(b, txn);
  put_u32(b + 8, begin_len);
  memcpy(b + 12, begin, begin_len);
  put_u32(b + 12 + begin_len, end_len);
  memcpy(b + 16 + begin_len, end, end_len);
  int st = rpc(db, 5, b, blen, NULL, NULL);
  free(b);
  return st;
}

int fdbtpu_txn_set_option(FDBTPU_Database *db, uint64_t txn,
                          const uint8_t *option, uint32_t option_len) {
  uint32_t blen = 8 + 4 + option_len;
  uint8_t *b = (uint8_t *)malloc(blen);
  put_u64(b, txn);
  put_u32(b + 8, option_len);
  memcpy(b + 12, option, option_len);
  int st = rpc(db, 13, b, blen, NULL, NULL);
  free(b);
  return st;
}

int fdbtpu_txn_watch(FDBTPU_Database *db, uint64_t txn, const uint8_t *key,
                     uint32_t key_len, int64_t *version) {
  uint32_t blen = 8 + 4 + key_len;
  uint8_t *b = (uint8_t *)malloc(blen);
  put_u64(b, txn);
  put_u32(b + 8, key_len);
  memcpy(b + 12, key, key_len);
  uint8_t *out = NULL;
  uint32_t out_len = 0;
  int st = rpc(db, 14, b, blen, &out, &out_len);
  free(b);
  *version = 0;
  if (st == 0 && out_len >= 8) *version = (int64_t)get_u64(out);
  free(out);
  return st;
}

int fdbtpu_txn_atomic_add(FDBTPU_Database *db, uint64_t txn,
                          const uint8_t *key, uint32_t key_len, int64_t delta) {
  uint32_t blen = 8 + 4 + key_len + 8;
  uint8_t *b = (uint8_t *)malloc(blen);
  put_u64(b, txn);
  put_u32(b + 8, key_len);
  memcpy(b + 12, key, key_len);
  put_i64(b + 12 + key_len, delta);
  int st = rpc(db, 10, b, blen, NULL, NULL);
  free(b);
  return st;
}

int fdbtpu_txn_get(FDBTPU_Database *db, uint64_t txn, const uint8_t *key,
                   uint32_t key_len, int *present, uint8_t **val,
                   uint32_t *val_len) {
  uint32_t blen = 8 + 4 + key_len;
  uint8_t *b = (uint8_t *)malloc(blen);
  put_u64(b, txn);
  put_u32(b + 8, key_len);
  memcpy(b + 12, key, key_len);
  uint8_t *out = NULL;
  uint32_t out_len = 0;
  int st = rpc(db, 6, b, blen, &out, &out_len);
  free(b);
  *present = 0;
  *val = NULL;
  *val_len = 0;
  if (st == 0 && out_len >= 5) {
    *present = out[0];
    uint32_t vlen = get_u32(out + 1);
    if (*present && vlen <= out_len - 5) {
      *val = (uint8_t *)malloc(vlen ? vlen : 1);
      memcpy(*val, out + 5, vlen);
      *val_len = vlen;
    }
  }
  free(out);
  return st;
}

int fdbtpu_txn_get_range(FDBTPU_Database *db, uint64_t txn,
                         const uint8_t *begin, uint32_t begin_len,
                         const uint8_t *end, uint32_t end_len, uint32_t limit,
                         uint32_t *n_rows, uint8_t **blob, uint32_t *blob_len) {
  uint32_t blen = 8 + 4 + begin_len + 4 + end_len + 4;
  uint8_t *b = (uint8_t *)malloc(blen);
  put_u64(b, txn);
  put_u32(b + 8, begin_len);
  memcpy(b + 12, begin, begin_len);
  put_u32(b + 12 + begin_len, end_len);
  memcpy(b + 16 + begin_len, end, end_len);
  put_u32(b + 16 + begin_len + end_len, limit);
  uint8_t *out = NULL;
  uint32_t out_len = 0;
  int st = rpc(db, 7, b, blen, &out, &out_len);
  free(b);
  *n_rows = 0;
  *blob = NULL;
  *blob_len = 0;
  if (st == 0 && out_len >= 4) {
    *n_rows = get_u32(out);
    *blob_len = out_len - 4;
    if (*blob_len) {
      *blob = (uint8_t *)malloc(*blob_len);
      memcpy(*blob, out + 4, *blob_len);
    }
  }
  free(out);
  return st;
}

/* wire KeySelector: u32 klen, key, u8 or_equal, i32 offset (5 fixed bytes) */
static uint32_t put_sel(uint8_t *p, const uint8_t *key, uint32_t key_len,
                        int or_equal, int32_t offset) {
  put_u32(p, key_len);
  memcpy(p + 4, key, key_len);
  p[4 + key_len] = or_equal ? 1 : 0;
  memcpy(p + 5 + key_len, &offset, 4);
  return 4 + key_len + 5;
}

int fdbtpu_txn_get_key(FDBTPU_Database *db, uint64_t txn, const uint8_t *key,
                       uint32_t key_len, int or_equal, int32_t offset,
                       uint8_t **resolved, uint32_t *resolved_len) {
  uint32_t blen = 8 + 4 + key_len + 5;
  uint8_t *b = (uint8_t *)malloc(blen);
  put_u64(b, txn);
  put_sel(b + 8, key, key_len, or_equal, offset);
  uint8_t *out = NULL;
  uint32_t out_len = 0;
  int st = rpc(db, 15, b, blen, &out, &out_len);
  free(b);
  *resolved = NULL;
  *resolved_len = 0;
  if (st == 0 && out_len >= 4) {
    uint32_t rlen = get_u32(out);
    if (rlen <= out_len - 4) {
      *resolved = (uint8_t *)malloc(rlen ? rlen : 1);
      memcpy(*resolved, out + 4, rlen);
      *resolved_len = rlen;
    }
  }
  free(out);
  return st;
}

int fdbtpu_txn_get_range_selector(
    FDBTPU_Database *db, uint64_t txn,
    const uint8_t *bkey, uint32_t bkey_len, int b_or_equal, int32_t b_offset,
    const uint8_t *ekey, uint32_t ekey_len, int e_or_equal, int32_t e_offset,
    uint32_t limit, uint32_t *n_rows, uint8_t **blob, uint32_t *blob_len) {
  uint32_t blen = 8 + (4 + bkey_len + 5) + (4 + ekey_len + 5) + 4;
  uint8_t *b = (uint8_t *)malloc(blen);
  put_u64(b, txn);
  uint32_t off = 8;
  off += put_sel(b + off, bkey, bkey_len, b_or_equal, b_offset);
  off += put_sel(b + off, ekey, ekey_len, e_or_equal, e_offset);
  put_u32(b + off, limit);
  uint8_t *out = NULL;
  uint32_t out_len = 0;
  int st = rpc(db, 16, b, blen, &out, &out_len);
  free(b);
  *n_rows = 0;
  *blob = NULL;
  *blob_len = 0;
  if (st == 0 && out_len >= 4) {
    *n_rows = get_u32(out);
    *blob_len = out_len - 4;
    if (*blob_len) {
      *blob = (uint8_t *)malloc(*blob_len);
      memcpy(*blob, out + 4, *blob_len);
    }
  }
  free(out);
  return st;
}

int fdbtpu_txn_commit(FDBTPU_Database *db, uint64_t txn, int64_t *version) {
  uint8_t body[8];
  put_u64(body, txn);
  uint8_t *out = NULL;
  uint32_t out_len = 0;
  int st = rpc(db, 8, body, 8, &out, &out_len);
  if (st == 0 && out_len >= 8) *version = get_i64(out);
  free(out);
  return st;
}

int fdbtpu_txn_get_read_version(FDBTPU_Database *db, uint64_t txn,
                                int64_t *version) {
  uint8_t body[8];
  put_u64(body, txn);
  uint8_t *out = NULL;
  uint32_t out_len = 0;
  int st = rpc(db, 11, body, 8, &out, &out_len);
  if (st == 0 && out_len >= 8) *version = get_i64(out);
  free(out);
  return st;
}

int fdbtpu_txn_on_error(FDBTPU_Database *db, uint64_t txn, int code) {
  if (code < 1 || code > 5) return code; /* not retryable */
  uint8_t body[12];
  put_u64(body, txn);
  int32_t c = (int32_t)code;
  memcpy(body + 8, &c, 4);
  int st = rpc(db, 9, body, 12, NULL, NULL);
  return st == 0 ? 0 : code;
}
